"""KV-cache autoregressive decoding: the runnable counterpart of the
reference's big-model-inference benchmark (BASELINE config #5, reference
``benchmarks/big_model_inference/README.md:27-37`` — model load time +
seconds/token with device_map dispatch).

Two paths, matching the two ways params can live:

- :func:`greedy_generate` — resident params (replicated or GSPMD-sharded):
  one jitted decode step; the cache is a stacked ``[L, B, max_len, Hkv, D]``
  pytree threaded functionally (donated each step), the layer loop is the same
  ``lax.scan`` as training so TP/FSDP shardings apply unchanged.
- :func:`generate_dispatched` — offloaded params (:class:`DispatchedParams`
  from ``device_map``-style dispatch): params are re-staged PER LAYER
  (``unstack_layer_params``) so paging granularity matches the reference's
  per-module hooks (``hooks.py:331-407``); each token pages layers through the
  execution device with one-stage-ahead prefetch while a jitted single-layer
  step computes.

Static shapes throughout: the cache is pre-sized to ``max_len`` and positions
mask the unwritten tail — no dynamic shapes reach XLA.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .models.transformer import LlamaConfig, apply_rope, rms_norm, rope_frequencies

__all__ = [
    "init_kv_cache",
    "generation_shardings",
    "serving_shardings",
    "greedy_generate",
    "sample_generate",
    "beam_generate",
    "sample_token_logits",
    "generate_dispatched",
    "unstack_layer_params",
]


def init_kv_cache(config: LlamaConfig, batch_size: int, max_len: int, dtype=jnp.bfloat16):
    """Stacked cache: {"k","v"}: [L, B, max_len, Hkv, D]."""
    shape = (config.n_layers, batch_size, max_len, config.n_kv_heads, config.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def generation_shardings(mesh, batch_size: int, config: LlamaConfig):
    """(prompt_sharding, cache_sharding) for decoding over ``mesh`` — the
    multi-chip leg of BASELINE config #5 ("dispatch_model generate, multi-chip
    sharding"; reference shards generate via ``device_map`` across GPUs,
    ``big_modeling.py:309``; here the TPU-native form is GSPMD over the mesh).

    Placement policy (an axis is used only where it divides evenly; anything
    else stays replicated over that axis):

    - batch over the data axes (``dp_replicate``/``dp_shard``/``dp``), claimed
      greedily one axis at a time while the joint shard count still divides the
      batch — batched serving parallelism;
    - KV heads over ``tp`` — with the params TP-sharded by
      ``models.transformer.llama_shard_rules`` this reproduces the Megatron
      decode dataflow: column-parallel QKV writes head-sharded cache entries,
      attention runs per-head-shard, row-parallel ``wo`` psums the output.

    Single-controller view: callers pass the GLOBAL batch (the driver/test CPU
    mesh and the axon single-chip tunnel are both fully addressable; multihost
    serving would hand each process its slice via
    ``jax.make_array_from_process_local_data`` before calling decode).
    """
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    axes = dict(mesh.shape)
    # greedy per-axis: claim each data axis whose size still divides the batch
    used: list = []
    used_size = 1
    for a in ("dp_replicate", "dp_shard", "dp"):
        size = axes.get(a, 1)
        if size > 1 and batch_size % (used_size * size) == 0:
            used.append(a)
            used_size *= size
    batch: Any = None if not used else (used[0] if len(used) == 1 else tuple(used))
    tp = "tp" if axes.get("tp", 1) > 1 and config.n_kv_heads % axes["tp"] == 0 else None
    prompt_sharding = NamedSharding(mesh, P(batch, None))
    # cache leaves: [L, B, max_len, Hkv, D]
    cache_sharding = NamedSharding(mesh, P(None, batch, None, tp, None))
    return prompt_sharding, cache_sharding


def serving_shardings(mesh, config: LlamaConfig):
    """NamedSharding for the serving engine's paged block pool
    ``[L, num_blocks, block_size, Hkv, D]`` — the paged-cache leg of the same
    placement policy as :func:`generation_shardings`: KV heads over ``tp``
    (where divisible) so the Megatron decode dataflow carries over unchanged;
    the block axis stays replicated because block tables address the WHOLE
    pool (any sequence may hold any block, so there is no batch axis to
    shard — batch parallelism for serving is a scheduler concern: run one
    engine per data-parallel replica).

    The spec is CANONICALIZED (PR 9's ``canonicalize_spec``: trailing
    ``None`` dims trimmed) so the placed pool's sharding compares equal to
    the canonical form GSPMD hands back on every step OUTPUT. The
    non-canonical ``P(None, None, None, tp, None)`` made the first warmed
    prefill bucket — the only one compiled against the freshly
    ``device_put`` pool — re-specialize on its first steady-state call on a
    multi-device mesh (the "4x2 recompile" noted in PR 14)."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from .parallel.sharding import canonicalize_spec

    axes = dict(mesh.shape)
    tp = "tp" if axes.get("tp", 1) > 1 and config.n_kv_heads % axes["tp"] == 0 else None
    return NamedSharding(mesh, canonicalize_spec(P(None, None, None, tp, None), axes))


def _place_for_mesh(mesh, prompt_ids, cache, config):
    """device_put prompt + cache per :func:`generation_shardings`."""
    prompt_sharding, cache_sharding = generation_shardings(mesh, prompt_ids.shape[0], config)
    prompt_ids = jax.device_put(prompt_ids, prompt_sharding)
    cache = jax.tree_util.tree_map(lambda c: jax.device_put(c, cache_sharding), cache)
    return prompt_ids, cache


def _masked_attention(q, k_cache, v_cache, allow, scale=None):
    """The decode attention core shared by the contiguous path here and the
    paged path (``serving.kv_pager.paged_attention``): q ``[B, S, H, D]``
    against caches ``[B, T, Hkv, D]`` under a boolean ``allow`` mask
    broadcastable to ``[B, H, S, T]``. One implementation so the two paths
    cannot drift — masked slots contribute EXACTLY 0 to the softmax (the
    ``finfo.min`` fill underflows to 0.0 after the max-subtraction), which is
    what makes paged decode bitwise-identical to contiguous decode even
    though the gathered ``T`` differs."""
    B, S, H, D = q.shape
    hkv = k_cache.shape[2]
    # GQA head-repeat: the H/Hkv ratio is fixed per model config, so this
    # shape branch specializes exactly once — not a per-step recompile
    if hkv != H:  # jaxlint: disable=R2
        rep = H // hkv
        k_cache = jnp.repeat(k_cache, rep, axis=2)
        v_cache = jnp.repeat(v_cache, rep, axis=2)
    scale = 1.0 / np.sqrt(D) if scale is None else scale
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k_cache,
                        preferred_element_type=jnp.float32) * scale
    logits = jnp.where(allow, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v_cache)


def _cached_attention(q, k_cache, v_cache, q_positions, scale=None):
    """q: [B, S, H, D]; caches [B, max_len, Hkv, D]; q_positions [S] — attend
    causally over all cache slots with position <= the query's position."""
    max_len = k_cache.shape[1]
    kv_pos = jnp.arange(max_len)
    allow = kv_pos[None, :] <= q_positions[:, None]  # [S, max_len]
    return _masked_attention(q, k_cache, v_cache, allow[None, None], scale)


def _project_qkv(layer_params, x, positions, cos, sin, config):
    """Shared QKV projection + RoPE for the cached-decode layer step: x
    ``[B, S, dim]``, per-row ``positions [B, S]``. Returns ``(q, k, v)`` in
    BSHD; used by both the contiguous layer step here and the paged one in
    ``serving.engine`` so projection math cannot drift between them."""
    B, S, _ = x.shape
    q = (x @ layer_params["wq"]["kernel"]).reshape(B, S, config.n_heads, config.head_dim)
    k = (x @ layer_params["wk"]["kernel"]).reshape(B, S, config.n_kv_heads, config.head_dim)
    v = (x @ layer_params["wv"]["kernel"]).reshape(B, S, config.n_kv_heads, config.head_dim)
    q = apply_rope(q, cos, sin, positions=positions)
    k = apply_rope(k, cos, sin, positions=positions)
    return q, k, v


def _layer_step(layer_params, h, k_cache, v_cache, positions, cos, sin, config, mesh=None):
    """One decoder layer over S tokens at ``positions``, updating [B,max,·,·]
    caches in place (dynamic_update_slice along the sequence axis)."""
    B, S, _ = h.shape
    x = rms_norm(h, layer_params["attn_norm"]["scale"], config.norm_eps)
    q, k, v = _project_qkv(
        layer_params, x, jnp.broadcast_to(positions[None], (B, S)), cos, sin, config
    )
    start = positions[0]
    k_cache = jax.lax.dynamic_update_slice(k_cache, k.astype(k_cache.dtype), (0, start, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(v_cache, v.astype(v_cache.dtype), (0, start, 0, 0))
    attn = _cached_attention(q, k_cache, v_cache, positions)
    h = h + attn.reshape(B, S, -1) @ layer_params["wo"]["kernel"]
    x = rms_norm(h, layer_params["mlp_norm"]["scale"], config.norm_eps)
    # MoE capacity: DECODE steps (S == 1) route only the B new tokens as one
    # tiny group, where the training-time capacity ceil(top_k*cf*g/E) would
    # drop tokens the full-sequence forward keeps (silent divergence) — floor
    # the factor at E/top_k there so per-step routing is drop-free (Switch/
    # GShard-style raised eval capacity; cost is bounded by the tiny group).
    # PREFILL (S > 1) keeps the training factor: its routing group equals the
    # full forward's at that length, and the floor would blow dispatch memory
    # up to O(g^2·E) on long prompts. Aux loss is irrelevant at inference.
    from .models.transformer import llama_ffn

    capacity_factor = None
    # S == 1 is the decode-vs-prefill split: exactly the two-program shape
    # bucketing the decode path is built around, not an accidental retrace
    if config.moe_experts > 0 and S == 1:  # jaxlint: disable=R2
        capacity_factor = max(config.moe_capacity_factor, config.moe_experts / config.moe_top_k)
    y, _ = llama_ffn(layer_params, x, config, mesh=mesh, capacity_factor=capacity_factor)
    h = h + y
    return h, k_cache, v_cache


def _forward_cached(params, ids, cache, start_pos, config: LlamaConfig, mesh=None):
    """Forward S tokens starting at ``start_pos`` against the cache.
    Returns (logits [B, S, vocab], new_cache)."""
    cos, sin = rope_frequencies(config.head_dim, config.max_seq_len, config.rope_theta)
    cos, sin = jnp.asarray(cos), jnp.asarray(sin)
    h = params["embed_tokens"]["embedding"][ids]
    S = ids.shape[1]
    positions = start_pos + jnp.arange(S)

    def layer(carry, xs):
        h = carry
        layer_params, k_c, v_c = xs
        h, k_c, v_c = _layer_step(
            layer_params, h, k_c, v_c, positions, cos, sin, config, mesh=mesh
        )
        return h, (k_c, v_c)

    h, (k_new, v_new) = jax.lax.scan(
        layer, h, (params["layers"], cache["k"], cache["v"]),
        unroll=config.unroll_layers,
    )
    h = rms_norm(h, params["final_norm"]["scale"], config.norm_eps)
    if config.tie_embeddings:
        logits = h @ params["embed_tokens"]["embedding"].T
    else:
        logits = h @ params["lm_head"]["kernel"]
    return logits, {"k": k_new, "v": v_new}


def sample_token_logits(logits, key, *, temperature: float = 1.0, top_k: int = 0,
                        top_p: float = 1.0):
    """One sampling step over ``logits [B, V]`` (jit-friendly; knobs are
    Python-static): temperature scaling, then top-k truncation, then nucleus
    (top-p) — the standard HF sampler composition. ``temperature == 0`` is
    greedy argmax."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    logits = logits.astype(jnp.float32) / temperature
    if top_k and top_k > 0:
        k = min(top_k, logits.shape[-1])  # HF clamps oversize top_k
        kth = jax.lax.top_k(logits, k)[0][..., -1:]  # [B, 1]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        cum = jnp.cumsum(jax.nn.softmax(sorted_logits, axis=-1), axis=-1)
        # smallest prefix reaching mass >= top_p (always keeps >= 1 token)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1)


def _cached_generate(
    params,
    prompt_ids,  # [B, S_prompt] (non-ragged; pad+mask upstream if needed)
    config: LlamaConfig,
    max_new_tokens: int,
    eos_token_id: Optional[int],
    cache_dtype,
    return_stats: bool,
    warmup: bool,
    select,  # (logits [B, V], key) -> next token [B]
    rng_key,
    mesh=None,
):
    """Shared KV-cache decode core: prefill once, then the ENTIRE decode loop
    in one compiled ``lax.scan`` (a single host round-trip — per-token fetches
    would serialize on host/ICI latency). Sequences that hit ``eos_token_id``
    keep emitting it; there is no data-dependent early exit under jit.

    With ``mesh``, the prompt and KV cache are placed per
    :func:`generation_shardings` (batch over data axes, KV heads over ``tp``)
    and GSPMD propagates the params' shardings through the compiled scan —
    params should already be on the mesh (``parallel.sharding.shard_params``
    with ``models.transformer.llama_shard_rules``)."""
    prompt_ids = jnp.asarray(prompt_ids)
    B, S = prompt_ids.shape
    max_len = S + max_new_tokens
    cache = init_kv_cache(config, B, max_len, cache_dtype)
    if mesh is not None:
        prompt_ids, cache = _place_for_mesh(mesh, prompt_ids, cache, config)
    if rng_key is None:
        rng_key = jax.random.PRNGKey(0)

    prefill = jax.jit(partial(_forward_cached, config=config, mesh=mesh))

    @partial(jax.jit, donate_argnums=(1,))
    def decode_all(params, cache, first_tok, key):
        def body(carry, i):
            tok, finished, cache = carry
            logits, cache = _forward_cached(params, tok[:, None], cache, S + i - 1, config, mesh=mesh)
            nxt = select(logits[:, -1], jax.random.fold_in(key, i)).astype(tok.dtype)
            if eos_token_id is not None:
                nxt = jnp.where(finished, eos_token_id, nxt)
                finished = jnp.logical_or(finished, nxt == eos_token_id)
            return (nxt, finished, cache), nxt

        finished = (
            first_tok == eos_token_id if eos_token_id is not None else jnp.zeros((B,), bool)
        )
        (_, _, cache), toks = jax.lax.scan(
            body, (first_tok, finished, cache), jnp.arange(1, max_new_tokens)
        )
        return toks.T  # [B, max_new_tokens-1]

    def _first(logits):
        return select(logits[:, -1], jax.random.fold_in(rng_key, 0)).astype(prompt_ids.dtype)

    if warmup and max_new_tokens > 1:
        cache_w = init_kv_cache(config, B, max_len, cache_dtype)
        if mesh is not None:
            _, cache_w = _place_for_mesh(mesh, prompt_ids, cache_w, config)
        logits_w, cache_w = prefill(params, prompt_ids, cache_w, jnp.int32(0))
        jax.device_get(decode_all(params, cache_w, _first(logits_w), rng_key))

    t0 = time.time()
    logits, cache = prefill(params, prompt_ids, cache, jnp.int32(0))
    first_tok = _first(logits)
    first_host = np.asarray(jax.device_get(first_tok))  # forces prefill for timing
    prefill_s = time.time() - t0

    t0 = time.time()
    if max_new_tokens > 1:
        rest = np.asarray(jax.device_get(decode_all(params, cache, first_tok, rng_key)))
    else:
        rest = np.zeros((B, 0), first_host.dtype)
    decode_s = time.time() - t0
    generated = np.concatenate(
        [np.asarray(jax.device_get(prompt_ids)), first_host[:, None], rest], axis=1
    )
    if return_stats:
        n_decoded = max(max_new_tokens - 1, 1)
        return generated, {
            "prefill_seconds": prefill_s,
            "decode_tokens_per_sec": n_decoded * B / max(decode_s, 1e-9),
            "seconds_per_token": decode_s / n_decoded,
        }
    return generated


def greedy_generate(
    params,
    prompt_ids,
    config: LlamaConfig,
    max_new_tokens: int = 32,
    eos_token_id: Optional[int] = None,
    cache_dtype=jnp.bfloat16,
    return_stats: bool = False,
    warmup: bool = False,
    mesh=None,
):
    """Jitted KV-cache greedy decoding for resident (replicated/sharded)
    params. Returns ids [B, S_prompt + max_new_tokens] (with a stats dict —
    prefill seconds, decode tokens/sec — when ``return_stats``); ``warmup``
    runs the decode once before timing so stats exclude compilation. Pass
    ``mesh`` (params already mesh-sharded) for multi-chip TP/DP decode — see
    :func:`generation_shardings`."""
    return _cached_generate(
        params, prompt_ids, config, max_new_tokens, eos_token_id, cache_dtype,
        return_stats, warmup,
        select=lambda logits, key: jnp.argmax(logits, axis=-1),
        rng_key=None,
        mesh=mesh,
    )


def sample_generate(
    params,
    prompt_ids,
    config: LlamaConfig,
    max_new_tokens: int = 32,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    rng_key=None,
    eos_token_id: Optional[int] = None,
    cache_dtype=jnp.bfloat16,
    return_stats: bool = False,
    warmup: bool = False,
    mesh=None,
):
    """Jitted KV-cache SAMPLED decoding (temperature / top-k / nucleus), the
    counterpart of HF ``generate(do_sample=True)``. The PRNG key is folded per
    step inside the compiled scan, so a given (key, prompt, knobs) triple is
    fully deterministic; ``temperature=0`` degrades to greedy. ``mesh`` as in
    :func:`greedy_generate`."""
    return _cached_generate(
        params, prompt_ids, config, max_new_tokens, eos_token_id, cache_dtype,
        return_stats, warmup,
        select=partial(sample_token_logits, temperature=temperature,
                       top_k=top_k, top_p=top_p),
        rng_key=rng_key,
        mesh=mesh,
    )


def beam_generate(
    params,
    prompt_ids,  # [B, S_prompt]
    config: LlamaConfig,
    num_beams: int = 4,
    max_new_tokens: int = 32,
    eos_token_id: Optional[int] = None,
    length_penalty: float = 1.0,
    cache_dtype=jnp.bfloat16,
    return_scores: bool = False,
    mesh=None,
):
    """Jitted KV-cache beam search (deterministic highest-probability decode).

    Standard beam algorithm: prefill once at batch B, tile the cache to
    ``B * num_beams``, then each scanned step expands every live beam over the
    vocab, keeps the top ``num_beams`` of ``num_beams * V`` candidates, and
    REORDERS the KV cache with the surviving beams' parent indices (a gather
    on the cache batch axis — the whole loop stays one compiled scan, like the
    greedy/sampled paths). Finished beams (hit ``eos_token_id``) are frozen:
    their only continuation is another eos at zero log-prob, so their score is
    carried unchanged. Final ranking divides by
    ``generated_length^length_penalty`` (modern HF >= 4.35 semantics;
    1.0 = average log-prob over the generated tokens).

    Returns ids ``[B, S_prompt + max_new_tokens]`` for the best beam
    (``return_scores=True`` adds the [B] length-normalized scores).
    """
    prompt_ids = jnp.asarray(prompt_ids)
    B, S = prompt_ids.shape
    K = num_beams
    max_len = S + max_new_tokens
    V = config.vocab_size

    cache = init_kv_cache(config, B, max_len, cache_dtype)
    if mesh is not None:
        # beams tile the batch axis inside jit (B -> B*K), which preserves the
        # batch-axis divisibility, so the same placement policy applies
        prompt_ids, cache = _place_for_mesh(mesh, prompt_ids, cache, config)
    prefill = jax.jit(partial(_forward_cached, config=config, mesh=mesh))
    logits, cache = prefill(params, prompt_ids, cache, jnp.int32(0))

    @jax.jit
    def beam_all(params, cache, last_logits):
        # tile the cache over beams: [L, B, ...] -> [L, B*K, ...]
        cache = jax.tree_util.tree_map(
            lambda c: jnp.repeat(c, K, axis=1), cache
        )
        logp0 = jax.nn.log_softmax(last_logits.astype(jnp.float32), axis=-1)  # [B, V]
        scores0, tok0 = jax.lax.top_k(logp0, K)  # [B, K]
        finished0 = (
            tok0 == eos_token_id if eos_token_id is not None else jnp.zeros((B, K), bool)
        )
        # modern HF (>= 4.35) normalizes by GENERATED length only
        # (GenerationMixin._update_finished_beams: cur_len+1-decoder_prompt_len);
        # the pre-4.35 full-sequence divisor is legacy
        lengths0 = jnp.ones((B, K), jnp.int32)
        tokens0 = jnp.zeros((B, K, max_new_tokens), jnp.int32)
        tokens0 = tokens0.at[:, :, 0].set(tok0)

        def body(carry, i):
            tokens, scores, finished, lengths, cache = carry
            last = jax.lax.dynamic_index_in_dim(tokens, i - 1, axis=2)  # [B, K, 1]
            logits, cache = _forward_cached(
                params, last.reshape(B * K, 1), cache, S + i - 1, config, mesh=mesh
            )
            logp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), axis=-1)
            logp = logp.reshape(B, K, V)
            if eos_token_id is not None:
                # frozen beams may only emit eos again, at no score cost
                frozen = jnp.full((V,), -jnp.inf).at[eos_token_id].set(0.0)
                logp = jnp.where(finished[:, :, None], frozen[None, None], logp)
            cand = scores[:, :, None] + logp  # [B, K, V]
            new_scores, flat_idx = jax.lax.top_k(cand.reshape(B, K * V), K)
            parent = flat_idx // V  # [B, K]
            tok = (flat_idx % V).astype(jnp.int32)

            tokens = jnp.take_along_axis(tokens, parent[:, :, None], axis=1)
            tokens = tokens.at[:, :, i].set(tok)
            finished = jnp.take_along_axis(finished, parent, axis=1)
            lengths = jnp.take_along_axis(lengths, parent, axis=1)
            lengths = jnp.where(finished, lengths, lengths + 1)
            if eos_token_id is not None:
                finished = jnp.logical_or(finished, tok == eos_token_id)
            # reorder the cache: [L, B*K, ...] -> group beams -> gather parents
            def cache_reorder(c):
                shaped = c.reshape((c.shape[0], B, K) + c.shape[2:])
                idx = parent.reshape((1, B, K) + (1,) * (shaped.ndim - 3))
                return jnp.take_along_axis(shaped, idx, axis=2).reshape(c.shape)

            cache = jax.tree_util.tree_map(cache_reorder, cache)
            return (tokens, new_scores, finished, lengths, cache), None

        (tokens, scores, finished, lengths, cache), _ = jax.lax.scan(
            body,
            (tokens0, scores0, finished0, lengths0, cache),
            jnp.arange(1, max_new_tokens),
        )
        norm = scores / jnp.power(lengths.astype(jnp.float32), length_penalty)
        best = jnp.argmax(norm, axis=1)  # [B]
        best_tokens = jnp.take_along_axis(tokens, best[:, None, None], axis=1)[:, 0]
        best_score = jnp.take_along_axis(norm, best[:, None], axis=1)[:, 0]
        return best_tokens, best_score

    best_tokens, best_score = beam_all(params, cache, logits[:, -1])
    out = np.concatenate(
        [np.asarray(jax.device_get(prompt_ids)), np.asarray(jax.device_get(best_tokens))],
        axis=1,
    )
    if return_scores:
        return out, np.asarray(jax.device_get(best_score))
    return out


# ---------------------------------------------------------------------------
# dispatched (offloaded) decoding


def unstack_layer_params(params, config: LlamaConfig) -> dict:
    """Re-stage stacked-layer params into per-layer subtrees so device-map
    dispatch pages ONE layer at a time (the reference's per-module hook
    granularity). ``layer_07`` etc. sort correctly for stage ordering."""
    stages = {"embed_tokens": params["embed_tokens"]}
    for i in range(config.n_layers):
        stages[f"layer_{i:03d}"] = jax.tree_util.tree_map(lambda x: x[i], params["layers"])
    stages["final_norm"] = params["final_norm"]
    if not config.tie_embeddings:
        stages["lm_head"] = params["lm_head"]
    return stages


def generate_dispatched(
    dispatched,  # DispatchedParams over unstack_layer_params(...) stages
    prompt_ids,
    config: LlamaConfig,
    max_new_tokens: int = 32,
    eos_token_id: Optional[int] = None,
    cache_dtype=jnp.bfloat16,
    return_stats: bool = False,
    warmup: bool = False,
):
    """Greedy decoding with per-layer paged params (cpu/disk offload).

    Each forward pages layer stages through the execution device with
    one-ahead prefetch (reference ``AlignDevicesHook`` hot loop, §3.4); the
    jitted single-layer step is shared across layers so there is exactly one
    compile per (S, position-signature)."""
    prompt_ids = jnp.asarray(prompt_ids)
    B, S = prompt_ids.shape
    max_len = S + max_new_tokens
    cos, sin = rope_frequencies(config.head_dim, config.max_seq_len, config.rope_theta)
    cos, sin = jnp.asarray(cos), jnp.asarray(sin)

    per_layer_cache = [
        {
            "k": jnp.zeros((B, max_len, config.n_kv_heads, config.head_dim), cache_dtype),
            "v": jnp.zeros((B, max_len, config.n_kv_heads, config.head_dim), cache_dtype),
        }
        for _ in range(config.n_layers)
    ]

    layer_fn = jax.jit(
        lambda lp, h, kc, vc, positions: _layer_step(lp, h, kc, vc, positions, cos, sin, config)
    )
    embed_fn = jax.jit(lambda emb, ids: emb["embedding"][ids])

    norm_fn = jax.jit(lambda fp, h: rms_norm(h, fp["scale"], config.norm_eps))

    layer_names = [f"layer_{i:03d}" for i in range(config.n_layers)]

    def forward(ids, start_pos):
        positions = start_pos + jnp.arange(ids.shape[1])
        dispatched.prefetch("embed_tokens")
        h = embed_fn(dispatched["embed_tokens"], ids)
        for i, name in enumerate(layer_names):
            if i + 1 < len(layer_names):
                dispatched.prefetch(layer_names[i + 1])
            lp = dispatched[name]
            cache_i = per_layer_cache[i]
            h, cache_i["k"], cache_i["v"] = layer_fn(
                lp, h, cache_i["k"], cache_i["v"], positions
            )
            dispatched.release(name)
        h = norm_fn(dispatched["final_norm"], h)
        if config.tie_embeddings:
            emb = dispatched["embed_tokens"]
            logits = h @ emb["embedding"].T
        else:
            logits = h @ dispatched["lm_head"]["kernel"]
        return logits

    t0 = time.time()
    logits = forward(prompt_ids, jnp.int32(0))
    next_tok = np.asarray(jax.device_get(jnp.argmax(logits[:, -1], axis=-1)))
    prefill_s = time.time() - t0

    tokens = [next_tok]
    finished = np.zeros((B,), bool)
    if eos_token_id is not None:
        finished |= next_tok == eos_token_id
    if warmup and max_new_tokens > 1:
        # the first seq-len-1 forward carries layer_fn's decode-signature
        # compile; greedy decode is deterministic, so repeating step 1 writes
        # the SAME cache values — the timed loop below re-runs it identically
        # with the compile excluded (same contract as greedy_generate warmup)
        logits = forward(jnp.asarray(tokens[-1])[:, None], jnp.int32(S))
        np.asarray(jax.device_get(logits[:, -1, 0]))  # force completion
    t0 = time.time()
    for i in range(1, max_new_tokens):
        logits = forward(jnp.asarray(tokens[-1])[:, None], jnp.int32(S + i - 1))
        tok = np.asarray(jax.device_get(jnp.argmax(logits[:, -1], axis=-1)))
        if eos_token_id is not None:
            tok = np.where(finished, eos_token_id, tok)
            finished |= tok == eos_token_id
        tokens.append(tok)
        if eos_token_id is not None and finished.all():
            break
    decode_s = time.time() - t0
    generated = np.concatenate(
        [np.asarray(jax.device_get(prompt_ids))] + [t[:, None] for t in tokens], axis=1
    )
    if return_stats:
        n_decoded = max(len(tokens) - 1, 1)
        return generated, {
            "prefill_seconds": prefill_s,
            "decode_tokens_per_sec": n_decoded * B / max(decode_s, 1e-9),
            "seconds_per_token": decode_s / n_decoded,
        }
    return generated
