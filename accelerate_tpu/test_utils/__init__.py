"""Test harness shipped with the package (reference ``src/accelerate/test_utils/``,
SURVEY.md §4): capability-gated decorators, backend probe, and launchable
assertion scripts under ``scripts/`` so any install can self-verify with
``accelerate-tpu test``."""

from .testing import (
    FakeSliceDevice,
    assert_allclose_tree,
    fake_slice_devices,
    get_backend,
    require_cpu,
    require_multi_device,
    require_pallas,
    require_single_device,
    require_tpu,
    skip,
    slow,
)
