"""Bundled end-to-end sanity script, run by ``accelerate-tpu test``.

Reference twin: ``test_utils/scripts/test_script.py`` (909 LoC of in-process
asserts — RNG sync ``:169``, DL preparation ``:187``, training parity
``training_check:449``, gather_for_metrics ``:623``). Asserts the same
behaviors on an SPMD mesh: initialization, collectives, sharded dataloading,
RNG synchronization, a real training run that must converge, and
metric-gathering with remainder trimming.
"""

from __future__ import annotations

import numpy as np


def init_check(accelerator):
    import jax

    assert accelerator.num_processes == jax.process_count()
    assert 0 <= accelerator.process_index < accelerator.num_processes
    assert accelerator.device is not None
    accelerator.wait_for_everyone()
    accelerator.print(f"init ok: {accelerator.num_processes} process(es), "
                      f"{jax.device_count()} device(s), mesh={accelerator.mesh}")


def ops_check(accelerator):
    import jax.numpy as jnp

    from accelerate_tpu.utils.operations import broadcast, gather, pad_across_processes, reduce

    n = jnp.arange(8.0)
    g = np.asarray(gather(n))
    assert g.shape[0] == 8 * max(accelerator.num_processes, 1), g.shape
    r = np.asarray(reduce(n, "sum"))
    np.testing.assert_allclose(r, np.arange(8.0) * accelerator.num_processes)
    b = np.asarray(broadcast(n))
    np.testing.assert_allclose(b, np.arange(8.0))
    p = pad_across_processes(jnp.ones((3, 2)), dim=0)
    assert np.asarray(p).shape[0] >= 3
    accelerator.print("ops ok")


def rng_check(accelerator):
    from accelerate_tpu.utils.random import synchronize_rng_states

    synchronize_rng_states(["python", "numpy"])
    vals = accelerator.gather_for_metrics([int(np.random.randint(0, 2**31))],
                                          use_gather_object=True)
    assert len(set(int(v) for v in np.asarray(vals).reshape(-1))) == 1, (
        f"RNG out of sync across processes: {vals}"
    )
    accelerator.print("rng sync ok")


def dl_check(accelerator):
    from accelerate_tpu import DataLoader

    data = {"x": np.arange(64, dtype=np.float32).reshape(64, 1)}

    class DS:
        def __len__(self):
            return 64

        def __getitem__(self, i):
            return {"x": data["x"][i]}

    dl = accelerator.prepare_data_loader(DataLoader(DS(), batch_size=8))
    seen = []
    for batch in dl:
        x = accelerator.gather(batch["x"])
        seen.append(np.asarray(x).reshape(-1))
    got = np.sort(np.concatenate(seen))
    np.testing.assert_allclose(got, np.arange(64, dtype=np.float32))
    accelerator.print("dataloader ok")


def training_check(accelerator):
    """Train y = w·x regression to (near-)zero loss through the full jitted path."""
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import DataLoader

    rng = np.random.default_rng(0)
    W = rng.normal(size=(4, 1)).astype(np.float32)
    X = rng.normal(size=(256, 4)).astype(np.float32)
    Y = X @ W

    class DS:
        def __len__(self):
            return 256

        def __getitem__(self, i):
            return {"x": X[i], "y": Y[i]}

    params = {"w": jnp.zeros((4, 1), jnp.float32)}
    opt = optax.sgd(0.1)
    dl = DataLoader(DS(), batch_size=16, shuffle=True, seed=0)
    params, opt, dl = accelerator.prepare(params, opt, dl)

    def loss_fn(p, batch):
        pred = batch["x"] @ p["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    step = accelerator.prepare_train_step(loss_fn, opt)
    opt_state = opt.opt_state
    metrics = None
    for _ in range(10):
        for batch in dl:
            params, opt_state, metrics = step(params, opt_state, batch)
    final = float(metrics["loss"])
    assert final < 1e-3, f"training did not converge: loss={final}"
    np.testing.assert_allclose(np.asarray(params["w"]), W, atol=0.05)
    accelerator.print(f"training ok (final loss {final:.2e})")


def metrics_check(accelerator):
    from accelerate_tpu import DataLoader

    n = 50  # not divisible by 8 — exercises remainder trimming

    class DS:
        def __len__(self):
            return n

        def __getitem__(self, i):
            return {"i": np.array([i], dtype=np.int32)}

    dl = accelerator.prepare_data_loader(DataLoader(DS(), batch_size=8))
    collected = []
    for batch in dl:
        collected.append(np.asarray(accelerator.gather_for_metrics(batch["i"])).reshape(-1))
    got = np.sort(np.concatenate(collected))
    np.testing.assert_allclose(got, np.arange(n))
    accelerator.print("gather_for_metrics ok")


def trigger_check(accelerator):
    if accelerator.is_main_process:
        accelerator.set_trigger()
    assert accelerator.check_trigger()
    assert not accelerator.check_trigger()  # reset after read
    accelerator.print("trigger ok")


def main():
    from accelerate_tpu import Accelerator

    accelerator = Accelerator()
    init_check(accelerator)
    ops_check(accelerator)
    rng_check(accelerator)
    dl_check(accelerator)
    metrics_check(accelerator)
    trigger_check(accelerator)
    training_check(accelerator)
    accelerator.print("All tests passed!")


if __name__ == "__main__":
    main()
