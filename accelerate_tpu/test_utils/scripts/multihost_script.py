"""Multi-process assertion script — run N copies under the launcher env protocol
(``ACCELERATE_COORDINATOR_ADDRESS``/``ACCELERATE_NUM_PROCESSES``/
``ACCELERATE_PROCESS_ID``) to prove the real multi-host code paths: process
rendezvous, host-level collectives, per-host data loading with global-array
assembly, dispatcher broadcast, training, checkpoint round-trip.

Behavioral model: the reference's bundled in-process assert script
(``/root/reference/src/accelerate/test_utils/scripts/test_script.py`` —
rng sync ``:169``, DL preparation ``:187/:247``, ``training_check:449``,
gather_for_metrics ``:623``), redesigned for the SPMD runtime: every process
asserts on every step, and batches are global ``jax.Array``s rather than
per-rank tensors.

Usage (each process): python -m accelerate_tpu.test_utils.scripts.multihost_script \
    --scenario all --tmpdir /tmp/xyz
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def check_topology(accelerator, expect_n):
    assert accelerator.num_processes == expect_n, (accelerator.num_processes, expect_n)
    assert accelerator.process_index == int(os.environ["ACCELERATE_PROCESS_ID"])
    accelerator.wait_for_everyone()


def check_ops(accelerator):
    import numpy as np

    from accelerate_tpu.utils import operations as ops

    n = accelerator.num_processes
    me = accelerator.process_index

    objs = ops.gather_object(("proc", me))
    assert objs == [("proc", i) for i in range(n)], objs

    payload = [{"value": 42, "blob": np.arange(3)}] if me == 0 else [None]
    out = ops.broadcast_object_list(payload)[0]
    assert out["value"] == 42 and out["blob"].tolist() == [0, 1, 2], out

    g = ops.gather(np.array([me], dtype=np.int32))
    assert np.asarray(g).reshape(-1).tolist() == list(range(n)), g

    r = ops.reduce(np.array([float(me + 1)]), "mean")
    expected = sum(range(1, n + 1)) / n
    assert abs(float(np.asarray(r).reshape(-1)[0]) - expected) < 1e-6, r

    r = ops.reduce(np.array([float(me + 1)]), "sum")
    assert abs(float(np.asarray(r).reshape(-1)[0]) - sum(range(1, n + 1))) < 1e-6, r

    # divergent host-local jax arrays must truly average (the reference's
    # per-rank all_reduce semantics — reduce:728), not silently no-op
    import jax.numpy as jnp

    r = ops.reduce({"p": jnp.full((3,), float(me + 1))}, "mean")
    expected = sum(range(1, n + 1)) / n
    assert np.allclose(np.asarray(r["p"]), expected), r
    r = ops.reduce(jnp.full((2,), float(me + 1)), "sum")
    assert np.allclose(np.asarray(r), float(sum(range(1, n + 1)))), r

    padded = ops.pad_across_processes(np.ones((2 + me, 3)), dim=0)
    assert np.asarray(padded).shape == (2 + (n - 1), 3), np.asarray(padded).shape

    b = ops.broadcast(np.array([me * 100 + 7]))
    assert int(np.asarray(b).reshape(-1)[0]) == 7, b

    with accelerator.split_between_processes(list(range(2 * n + 1))) as mine:
        sizes = ops.gather_object(len(mine))
        assert sum(sizes) == 2 * n + 1, sizes

    accelerator.wait_for_everyone()


def check_local_sgd(accelerator):
    """Multi-host LocalSGD: divergent per-process params must actually average
    on the k-step boundary (reference ``_sync_and_avg_model_params``)."""
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu.local_sgd import LocalSGD

    me = accelerator.process_index
    n = accelerator.num_processes
    params = {"w": jnp.full((4,), float(me + 1))}
    with LocalSGD(accelerator, model=params, local_sgd_steps=2, enabled=True) as ls:
        ls.step(params)
        params = ls.step(params)  # boundary → cross-process average
        expected = sum(range(1, n + 1)) / n
        assert np.allclose(np.asarray(params["w"]), expected), (params, expected)
    accelerator.wait_for_everyone()


def _row_dataset(n_rows):
    import numpy as np

    class DS:
        def __len__(self):
            return n_rows

        def __getitem__(self, i):
            return {"x": np.full((4,), float(i), dtype=np.float32), "idx": np.int32(i)}

    return DS()


def check_dataloader(accelerator, dispatch: bool):
    import numpy as np

    from accelerate_tpu import DataLoader

    n_rows = 16
    per_proc_bs = 4 // accelerator.num_processes if accelerator.num_processes <= 4 else 1
    dl = DataLoader(_row_dataset(n_rows), batch_size=per_proc_bs)
    prepared = accelerator.prepare_data_loader(dl)

    seen = []
    for batch in prepared:
        g = accelerator.gather(batch)
        idx = np.asarray(g["idx"]).reshape(-1)
        x0 = np.asarray(g["x"])[:, 0]
        # field consistency: x rows must carry their index value
        assert np.allclose(x0, idx.astype(np.float32)), (x0, idx)
        seen.extend(idx.tolist())
    # full coverage of the dataset, each row exactly once (even division here)
    assert sorted(seen) == list(range(n_rows)), sorted(seen)
    accelerator.wait_for_everyone()


def check_dispatcher(accelerator):
    import numpy as np

    from accelerate_tpu import DataLoader
    from accelerate_tpu.data_loader import prepare_data_loader

    n_rows = 8
    per_proc_bs = max(4 // accelerator.num_processes, 1)

    me = accelerator.process_index

    class RankZeroOnlyDS:
        """The dispatcher's documented use case: a source only rank 0 can read.
        Any non-main read is a hard failure (reference ``_fetch_batches:786`` —
        rank 0 next()s, everyone else receives)."""

        def __len__(self):
            return n_rows

        def __getitem__(self, i):
            if me != 0:
                raise RuntimeError(f"dataset read on non-main rank {me}")
            return {"x": np.full((4,), float(i), dtype=np.float32), "idx": np.int32(i)}

    dl = DataLoader(RankZeroOnlyDS(), batch_size=per_proc_bs)
    prepared = prepare_data_loader(
        dl,
        state=accelerator.state,
        mesh=accelerator.mesh,
        parallelism_config=accelerator.parallelism_config,
        dispatch_batches=True,
    )
    seen = []
    for batch in prepared:
        g = accelerator.gather(batch)
        seen.extend(np.asarray(g["idx"]).reshape(-1).tolist())
    assert sorted(seen) == list(range(n_rows)), sorted(seen)
    accelerator.wait_for_everyone()


def check_dispatcher_ragged(accelerator):
    """Tensor fast-path + uneven final batch (VERDICT r03 item 5): after the
    first (signature-establishing) batch, payloads go over the raw-array
    channel — broadcast_object_list must NOT be called per batch — and the
    ragged final global batch is padded on the wire but trimmed by
    ``gather_for_metrics`` so every sample appears exactly once."""
    import numpy as np

    import accelerate_tpu.utils.operations as ops
    from accelerate_tpu import DataLoader
    from accelerate_tpu.data_loader import prepare_data_loader

    # adaptive to the process count (2 procs: bs 4, rows 10; 3 procs: bs 6,
    # rows 15 — always 2 full batches + a ragged half batch)
    global_bs = 2 * accelerator.num_processes
    n_rows = global_bs * 2 + global_bs // 2
    me = accelerator.process_index

    class RankZeroOnlyDS:
        def __len__(self):
            return n_rows

        def __getitem__(self, i):
            if me != 0:
                raise RuntimeError(f"dataset read on non-main rank {me}")
            return {"x": np.full((4,), float(i), dtype=np.float32), "idx": np.int32(i)}

    object_casts = {"n": 0}
    real_bcast = ops.broadcast_object_list

    def counting_bcast(object_list, from_process=0):
        object_casts["n"] += 1
        return real_bcast(object_list, from_process)

    ops.broadcast_object_list = counting_bcast
    try:
        dl = DataLoader(RankZeroOnlyDS(), batch_size=global_bs, drop_last=False)
        prepared = prepare_data_loader(
            dl,
            state=accelerator.state,
            mesh=accelerator.mesh,
            parallelism_config=accelerator.parallelism_config,
            dispatch_batches=True,
        )
        seen = []
        n_batches = 0
        for batch in prepared:
            n_batches += 1
            g = accelerator.gather_for_metrics({"idx": batch["idx"]})
            seen.extend(np.asarray(g["idx"]).reshape(-1).tolist())
    finally:
        ops.broadcast_object_list = real_bcast
    assert n_batches == 3, n_batches
    # padded duplicates trimmed: exact cover, each row exactly once
    assert sorted(seen) == list(range(n_rows)), sorted(seen)
    if accelerator.num_processes > 1:
        # one object broadcast to establish the signature; the 2 remaining
        # batches (incl. the padded ragged one) ride the array fast-path
        assert object_casts["n"] == 1, object_casts["n"]

    # object-dtype leaves (strings) cannot ride the raw-bytes channel: the
    # dispatcher must keep them on the object channel, not crash mid-protocol
    n_str = 2 * accelerator.num_processes

    class StringDS:
        def __len__(self):
            return n_str

        def __getitem__(self, i):
            if me != 0:
                raise RuntimeError(f"dataset read on non-main rank {me}")
            return {"text": f"doc-{i}", "idx": np.int32(i)}

    dl2 = DataLoader(StringDS(), batch_size=accelerator.num_processes)
    prepared2 = prepare_data_loader(
        dl2,
        state=accelerator.state,
        mesh=accelerator.mesh,
        parallelism_config=accelerator.parallelism_config,
        dispatch_batches=True,
        device_placement=False,  # object leaves cannot be device-placed
    )
    texts = []
    for batch in prepared2:
        assert len(batch["text"]) == accelerator.num_processes
        texts.extend(str(t) for t in np.asarray(batch["text"]).tolist())
    assert sorted(texts) == sorted(f"doc-{i}" for i in range(n_str)), texts
    accelerator.wait_for_everyone()


def check_hybrid_mesh(accelerator):
    """Multi-slice DCN placement with PROCESSES as the granule
    (``ACCELERATE_HYBRID_MESH_GRANULE=process``): 2 OS processes x 2 local
    devices build a hybrid mesh whose ``dp_replicate`` rows are process-local
    (inner collectives stay "on ICI" = intra-process; the replica allreduce
    crosses the process boundary = "DCN"), then run a REAL sharded train step
    over it. The closest single-machine analogue of a 2-slice pod."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator, AcceleratorState, GradientState, ParallelismConfig, PartialState

    n_proc = accelerator.num_processes
    if n_proc < 2 or len(jax.devices()) != 4:
        print("hybrid_mesh scenario needs 2 procs x 2 devices; skipping", flush=True)
        return
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    os.environ["ACCELERATE_HYBRID_MESH_GRANULE"] = "process"
    try:
        pc = ParallelismConfig(dp_replicate_size=2, dp_shard_size=2)
        acc2 = Accelerator(parallelism_config=pc, rng_seed=0)
        mesh = acc2.mesh
        arr = mesh.devices  # (pp, dp_replicate, dp_shard, cp, sp, tp, ep)
        for rep in range(2):
            procs = {d.process_index for d in arr[0, rep].flat}
            assert len(procs) == 1, f"dp_replicate row {rep} spans processes {procs}"
        assert (
            {d.process_index for d in arr[0, 0].flat}
            != {d.process_index for d in arr[0, 1].flat}
        ), "replicas landed in the same process granule"

        params = {
            "w": np.zeros((8, 4), np.float32),
            "b": np.zeros((4,), np.float32),
        }
        params, opt = acc2.prepare(params, optax.sgd(0.1))

        def loss_fn(p, batch):
            pred = batch["x"] @ p["w"] + p["b"]
            return jnp.mean((pred - batch["y"]) ** 2)

        step = acc2.prepare_train_step(loss_fn, opt)
        from jax.sharding import NamedSharding, PartitionSpec as P

        rng = np.random.default_rng(0)  # identical on every process
        spec = NamedSharding(mesh, P(("dp_replicate", "dp_shard")))
        batch = {
            "x": jax.device_put(rng.normal(size=(8, 8)).astype(np.float32), spec),
            "y": jax.device_put(rng.normal(size=(8, 4)).astype(np.float32), spec),
        }
        params, opt_state, metrics = step(params, opt.opt_state, batch)
        loss = float(metrics["loss"])
        assert np.isfinite(loss), loss
    finally:
        os.environ.pop("ACCELERATE_HYBRID_MESH_GRANULE", None)
        # restore the baseline borg state: later scenarios share the outer
        # accelerator's state dict, which acc2's hybrid config overwrote
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        Accelerator(mixed_precision="no", rng_seed=0)
    accelerator.wait_for_everyone()
    print(f"hybrid mesh (process granule) train step OK, loss={loss:.4f}", flush=True)


def check_training(accelerator, tmpdir: str):
    """DP training across processes; writes the loss trajectory so the harness
    can diff process counts (parity = the reference's training_check)."""
    import jax
    import numpy as np
    import optax

    from accelerate_tpu import DataLoader

    rng = np.random.default_rng(0)
    X = rng.normal(size=(32, 8)).astype(np.float32)
    W_true = rng.normal(size=(8, 1)).astype(np.float32)
    Y = X @ W_true

    class DS:
        def __len__(self):
            return 32

        def __getitem__(self, i):
            return {"x": X[i], "y": Y[i]}

    global_bs = 8
    per_proc = global_bs // accelerator.num_processes
    params = {"w": np.zeros((8, 1), np.float32), "b": np.zeros((1,), np.float32)}
    params, opt, dl = accelerator.prepare(
        params, optax.sgd(0.1), DataLoader(DS(), batch_size=per_proc)
    )

    def loss_fn(p, batch):
        import jax.numpy as jnp

        pred = batch["x"] @ p["w"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    step = accelerator.prepare_train_step(loss_fn, opt, donate=False)
    opt_state = opt.opt_state
    losses = []
    for epoch in range(3):
        for batch in dl:
            params, opt_state, metrics = step(params, opt_state, batch)
            losses.append(float(np.asarray(metrics["loss"])))
    assert losses[-1] < losses[0], losses
    # parameters must be identical on every process (they are replicated/global)
    w_all = accelerator.gather_for_metrics(
        {"w": np.asarray(jax.device_get(params["w"])).reshape(1, -1)}, use_gather_object=True
    )
    if accelerator.is_main_process:
        with open(os.path.join(tmpdir, f"losses_np{accelerator.num_processes}.json"), "w") as f:
            json.dump(losses, f)
    accelerator.wait_for_everyone()
    return params, opt_state


def check_checkpoint(accelerator, tmpdir: str, params, opt_state):
    import jax
    import numpy as np

    ckpt = os.path.join(tmpdir, f"ckpt_np{accelerator.num_processes}")
    accelerator.save_state(ckpt, params=params, opt_state=opt_state)
    # every process must have written its RNG snapshot
    rng_file = os.path.join(ckpt, f"random_states_{accelerator.process_index}.pkl")
    assert os.path.exists(rng_file), rng_file

    zeros = jax.tree_util.tree_map(lambda x: np.zeros_like(np.asarray(jax.device_get(x))), params)
    restored = accelerator.load_state(ckpt, params=jax.tree_util.tree_map(
        lambda z, live: jax.device_put(z, live.sharding) if hasattr(live, "sharding") else z,
        zeros, params,
    ))
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(jax.device_get(restored[k])), np.asarray(jax.device_get(params[k]))
        )
    accelerator.wait_for_everyone()


def check_sharded_checkpoint(accelerator, tmpdir: str):
    """FSDP-sharded save with NO host holding the full state, reload onto a
    refactored mesh, resume to identical losses (reference
    ``utils/fsdp_utils.py:103-414`` DCP sharded checkpoints)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from accelerate_tpu.sharded_checkpoint import is_sharded_checkpoint

    n_dev = jax.device_count()
    assert n_dev >= 2, "needs >= 2 global devices"
    mesh = Mesh(np.array(jax.devices()), ("dp_shard",))
    dim = 8 * n_dev

    rng = np.random.default_rng(1)
    W0 = rng.normal(size=(dim, 4)).astype(np.float32) * 0.1
    params = {"w": jax.device_put(W0, NamedSharding(mesh, P("dp_shard")))}
    opt = optax.adam(0.05)
    opt_state = opt.init(params)  # momenta inherit the params' sharding

    X = rng.normal(size=(16, dim)).astype(np.float32)
    Y = rng.normal(size=(16, 4)).astype(np.float32)

    @jax.jit
    def step(p, s, x, y):
        def loss_fn(p):
            return jnp.mean((x @ p["w"] - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, s = opt.update(grads, s, p)
        return optax.apply_updates(p, updates), s, loss

    for _ in range(2):
        params, opt_state, _ = step(params, opt_state, X, Y)

    # save: auto-shards because leaves span hosts (not fully addressable)
    ckpt = os.path.join(tmpdir, "sharded_ckpt")
    accelerator.save_state(ckpt, params=params, opt_state=opt_state)
    accelerator.wait_for_everyone()
    assert is_sharded_checkpoint(ckpt, "model") and is_sharded_checkpoint(ckpt, "optimizer")
    assert not os.path.exists(os.path.join(ckpt, "model.npz"))

    # THE property: this host's shard file holds only its slice of the params,
    # never the full array (the reference's DCP FileSystemWriter contract).
    # Count elements from the index (format-agnostic: bin or npz container).
    import json

    me = accelerator.process_index
    with open(os.path.join(ckpt, f"model-shard-{me:05d}.index.json")) as f:
        index = json.load(f)
    stored = sum(
        int(np.prod([e - s for s, e in zip(c["start"], c["stop"])] or [1]))
        for meta in index["leaves"].values()
        for c in meta["chunks"]
    )
    full = dim * 4
    assert stored == full // accelerator.num_processes, (stored, full)
    # the index is self-reported; the BYTES on disk must agree (f32 leaves,
    # ≤64B alignment slack per chunk + container overhead)
    n_chunks = sum(len(m["chunks"]) for m in index["leaves"].values())
    for ext in (".bin", ".npz"):
        shard_path = os.path.join(ckpt, f"model-shard-{me:05d}{ext}")
        if os.path.isfile(shard_path):
            disk = os.path.getsize(shard_path)
            assert disk <= stored * 4 + n_chunks * 64 + 1024, (disk, stored * 4)
            break
    else:
        raise AssertionError("no shard container file found")

    # reference trajectory: two more steps
    ref_losses = []
    p_ref, s_ref = params, opt_state
    for _ in range(2):
        p_ref, s_ref, loss = step(p_ref, s_ref, X, Y)
        ref_losses.append(float(loss))

    # reload onto a REFACTORED mesh: shard dim 1 instead of dim 0 ('b' must
    # span ALL devices — across both hosts — or the reshard test is vacuous)
    mesh_b = Mesh(np.array(jax.devices()).reshape(1, -1), ("a", "b"))
    template = {
        "w": jax.device_put(jnp.zeros((dim, 4)), NamedSharding(mesh_b, P(None, "b")))
    }
    # template leaves must be GLOBAL arrays (opt.init outside jit would commit
    # scalars like adam's count to one local device)
    def _global_zeros(sd):
        spec = P(None, "b") if sd.shape == (dim, 4) else P()
        return jax.device_put(jnp.zeros(sd.shape, sd.dtype), NamedSharding(mesh_b, spec))

    opt_template = jax.tree_util.tree_map(_global_zeros, jax.eval_shape(opt.init, template))
    restored, restored_opt = accelerator.load_state(ckpt, params=template, opt_state=opt_template)
    assert restored["w"].sharding.spec == P(None, "b")

    resumed_losses = []
    p_new, s_new = restored, restored_opt
    for _ in range(2):
        p_new, s_new, loss = step(p_new, s_new, X, Y)
        resumed_losses.append(float(loss))
    for a, b in zip(ref_losses, resumed_losses):
        assert abs(a - b) < 1e-6, (ref_losses, resumed_losses)

    # host-local (fully addressable) leaves: exactly ONE process may write them
    # — divergent per-host values must deterministically restore to process 0's
    # copy, not whichever shard file sorts last
    from accelerate_tpu.sharded_checkpoint import load_sharded_pytree, save_sharded_pytree

    local_dir = os.path.join(tmpdir, "local_leaf_ckpt")
    os.makedirs(local_dir, exist_ok=True)
    accelerator.wait_for_everyone()
    me_f = float(accelerator.process_index)
    save_sharded_pytree({"local": jnp.full((4,), me_f), "shared": params["w"]}, local_dir)
    accelerator.wait_for_everyone()
    got = load_sharded_pytree(
        {"local": jnp.zeros((4,)), "shared": jax.device_put(jnp.zeros((dim, 4)), NamedSharding(mesh, P("dp_shard")))},
        local_dir,
    )
    assert np.allclose(np.asarray(got["local"]), 0.0), np.asarray(got["local"])
    accelerator.wait_for_everyone()


def check_generate(accelerator):
    """Mesh-sharded KV-cache decode ACROSS PROCESSES: params TP-sharded over a
    mesh spanning both hosts, the row-parallel ``wo`` psum rides the
    cross-process collective backend inside the compiled decode scan, and the
    (replicated) token output matches a single-device dense decode exactly
    (the multihost leg of BASELINE config #5; see
    ``generation.generation_shardings``)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from accelerate_tpu.generation import greedy_generate, sample_generate
    from accelerate_tpu.models.transformer import LlamaConfig, init_llama, llama_shard_rules
    from accelerate_tpu.parallel.sharding import shard_params

    n_dev = jax.device_count()
    assert n_dev >= 2, "needs >= 2 global devices"
    config = LlamaConfig(
        vocab_size=128, dim=32, n_layers=2, n_heads=4, n_kv_heads=2, max_seq_len=64
    )
    # tp must divide the KV heads or the cache stays replicated and the
    # head-sharded-decode contract this scenario pins is silently skipped
    assert config.n_kv_heads % n_dev == 0, (n_dev, config.n_kv_heads)

    params = init_llama(config, jax.random.PRNGKey(3))
    params = jax.tree_util.tree_map(lambda x: np.asarray(x, np.float32), params)
    prompt = np.asarray(
        jax.random.randint(jax.random.PRNGKey(4), (2, 6), 0, config.vocab_size), np.int32
    )

    # single-device dense reference (local to each process, identical inputs)
    ref = greedy_generate(params, prompt, config, max_new_tokens=5, cache_dtype=np.float32)

    mesh = Mesh(np.array(jax.devices()), ("tp",))
    sharded, _ = shard_params(params, mesh, rules=llama_shard_rules())
    out = greedy_generate(
        sharded, prompt, config, max_new_tokens=5, cache_dtype=np.float32, mesh=mesh
    )
    np.testing.assert_array_equal(ref, out)

    key = jax.random.PRNGKey(11)
    ref_s = sample_generate(params, prompt, config, max_new_tokens=5, temperature=0.8,
                            top_k=16, rng_key=key, cache_dtype=np.float32)
    out_s = sample_generate(sharded, prompt, config, max_new_tokens=5, temperature=0.8,
                            top_k=16, rng_key=key, cache_dtype=np.float32, mesh=mesh)
    np.testing.assert_array_equal(ref_s, out_s)


def check_zigzag_cp(accelerator):
    """Zig-zag ring attention with the cp axis SPANNING PROCESSES: the lane
    exchange and kv-pair rotation ppermutes ride the cross-host collective
    backend. Every process's addressable output shards must match the
    single-device reference slice exactly."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from accelerate_tpu.ops.attention import dot_product_attention
    from accelerate_tpu.parallel.long_context import make_context_parallel_attention
    from accelerate_tpu.parallelism_config import ParallelismConfig

    n_dev = jax.device_count()
    assert n_dev >= 2
    mesh = ParallelismConfig(cp_size=n_dev).build_mesh(jax.devices())
    rng = np.random.default_rng(11)  # identical on every process
    B, S, H, D = 2, 8 * n_dev, 4, 16
    q = rng.normal(size=(B, S, H, D)).astype(np.float32)
    k = rng.normal(size=(B, S, H, D)).astype(np.float32)
    v = rng.normal(size=(B, S, H, D)).astype(np.float32)
    ref = np.asarray(dot_product_attention(
        jax.device_put(q, jax.local_devices()[0]),
        jax.device_put(k, jax.local_devices()[0]),
        jax.device_put(v, jax.local_devices()[0]),
        causal=True, impl="xla",
    ))
    attn = make_context_parallel_attention(mesh, strategy="zigzag")
    spec = NamedSharding(mesh, P(None, "cp", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = jax.jit(lambda a, b, c: attn(a, b, c, causal=True))(qs, ks, vs)
    jax.block_until_ready(out)
    for shard in out.addressable_shards:
        np.testing.assert_allclose(
            np.asarray(shard.data), ref[shard.index], rtol=2e-4, atol=2e-5,
            err_msg=f"zigzag shard {shard.index} diverges from reference",
        )


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--scenario", default="all")
    parser.add_argument("--tmpdir", default="/tmp")
    args = parser.parse_args()

    from accelerate_tpu import Accelerator

    expect_n = int(os.environ.get("ACCELERATE_NUM_PROCESSES", 1))
    accelerator = Accelerator(mixed_precision="no", rng_seed=0)

    scenarios = args.scenario.split(",") if args.scenario != "all" else [
        "topology", "ops", "local_sgd", "dataloader", "dispatcher",
        "dispatcher_ragged", "training",
        "checkpoint", "sharded_checkpoint", "generate", "zigzag", "hybrid_mesh",
    ]
    params = opt_state = None
    for scenario in scenarios:
        if scenario == "topology":
            check_topology(accelerator, expect_n)
        elif scenario == "ops":
            check_ops(accelerator)
        elif scenario == "local_sgd":
            check_local_sgd(accelerator)
        elif scenario == "dataloader":
            check_dataloader(accelerator, dispatch=False)
        elif scenario == "dispatcher":
            check_dispatcher(accelerator)
        elif scenario == "dispatcher_ragged":
            check_dispatcher_ragged(accelerator)
        elif scenario == "hybrid_mesh":
            check_hybrid_mesh(accelerator)
        elif scenario == "training":
            params, opt_state = check_training(accelerator, args.tmpdir)
        elif scenario == "checkpoint":
            if params is None:
                params, opt_state = check_training(accelerator, args.tmpdir)
            check_checkpoint(accelerator, args.tmpdir, params, opt_state)
        elif scenario == "sharded_checkpoint":
            check_sharded_checkpoint(accelerator, args.tmpdir)
        elif scenario == "generate":
            check_generate(accelerator)
        elif scenario == "zigzag":
            check_zigzag_cp(accelerator)
        else:
            raise ValueError(f"unknown scenario {scenario}")
        print(f"[proc {accelerator.process_index}] scenario {scenario}: OK", flush=True)

    print(f"ALL OK proc={accelerator.process_index}/{accelerator.num_processes}", flush=True)


if __name__ == "__main__":
    main()
