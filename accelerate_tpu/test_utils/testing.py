"""Capability gating + helpers (reference ``test_utils/testing.py:83-616``:
``get_backend``, ``require_*`` decorators, ``slow``)."""

from __future__ import annotations

import functools
import os
import unittest

from ..utils.imports import is_pallas_available, is_tpu_available


def get_backend() -> tuple[str, int]:
    """(platform, device_count) — device-agnostic probe (reference
    ``testing.py:83-108`` returns (device, count, memory-fn))."""
    import jax

    return jax.default_backend(), jax.device_count()


def skip(reason: str):
    return unittest.skip(reason)


def _require(flag: bool, reason: str):
    def deco(fn):
        return unittest.skipUnless(flag, reason)(fn)

    return deco


def require_tpu(fn):
    return _require(is_tpu_available(), "test requires a TPU backend")(fn)


def require_cpu(fn):
    import jax

    return _require(jax.default_backend() == "cpu", "test requires CPU backend")(fn)


def require_single_device(fn):
    import jax

    return _require(jax.device_count() == 1, "test requires exactly 1 device")(fn)


def require_multi_device(fn):
    import jax

    return _require(jax.device_count() > 1, "test requires multiple devices")(fn)


def require_pallas(fn):
    return _require(is_pallas_available(), "test requires pallas (TPU backend)")(fn)


def slow(fn):
    """Gated behind RUN_SLOW=1 (reference ``testing.py`` ``slow``)."""
    run_slow = os.environ.get("RUN_SLOW", "0").lower() in ("1", "true", "yes")
    return unittest.skipUnless(run_slow, "slow test — set RUN_SLOW=1")(fn)


def assert_allclose_tree(a, b, rtol: float = 1e-5, atol: float = 1e-6, err_msg: str = ""):
    """Tree-wise ``np.testing.assert_allclose``."""
    import jax
    import numpy as np

    la, treedef_a = jax.tree_util.tree_flatten(a)
    lb, treedef_b = jax.tree_util.tree_flatten(b)
    assert treedef_a == treedef_b, f"tree structure mismatch: {treedef_a} vs {treedef_b}"
    for xa, xb in zip(la, lb):
        np.testing.assert_allclose(np.asarray(xa), np.asarray(xb), rtol=rtol, atol=atol,
                                   err_msg=err_msg)


class FakeSliceDevice:
    """Stand-in for a multislice TPU device, carrying exactly the attributes
    ``mesh_utils.create_hybrid_device_mesh`` / ``create_device_mesh`` touch
    (``slice_index`` grouping + the coords/platform probes). Used to validate
    DCN-aware mesh construction without multislice hardware."""

    def __init__(self, i: int, slice_index: int, per_slice: int):
        self.id = i
        self.slice_index = slice_index
        self.process_index = slice_index
        self.platform = "cpu"
        self.device_kind = "fake"
        self.coords = (i % per_slice, 0, 0)
        self.core_on_chip = 0

    def __repr__(self):
        return f"FakeSliceDevice(id={self.id}, slice={self.slice_index})"


def fake_slice_devices(n: int = 8, num_slices: int = 2) -> list:
    """``n`` fake devices split evenly over ``num_slices`` slices."""
    per_slice = n // num_slices
    return [FakeSliceDevice(i, i // per_slice, per_slice) for i in range(n)]


def find_free_port() -> int:
    import socket

    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def execute_multiprocess(
    script_args: list[str],
    num_processes: int = 2,
    env_extra: dict | None = None,
    timeout: int = 420,
    devices_per_process: int = 1,
) -> list[str]:
    """Launch ``num_processes`` real OS processes running
    ``python <script_args>`` under the multi-host env protocol
    (``ACCELERATE_COORDINATOR_ADDRESS``/``NUM_PROCESSES``/``PROCESS_ID``) with a
    CPU backend, wait for all, assert rc==0 everywhere, and return each
    process's combined output.

    The TPU-native twin of the reference's ``execute_subprocess_async``
    (``test_utils/testing.py:764``) + ``DEFAULT_LAUNCH_COMMAND``: the reference
    proves cross-process parity by launching its bundled assert scripts; so do
    we, with ``jax.distributed`` rendezvous instead of torchrun.
    """
    import subprocess
    import sys

    port = find_free_port()
    procs = []
    for i in range(num_processes):
        env = os.environ.copy()
        env.pop("XLA_FLAGS", None)
        if devices_per_process > 1:
            env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devices_per_process}"
        env["ACCELERATE_USE_CPU"] = "true"
        env["ACCELERATE_COORDINATOR_ADDRESS"] = f"localhost:{port}"
        env["ACCELERATE_NUM_PROCESSES"] = str(num_processes)
        env["ACCELERATE_PROCESS_ID"] = str(i)
        env.update(env_extra or {})
        procs.append(
            subprocess.Popen(
                [sys.executable, *script_args],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outputs = []
    failed = []
    for i, proc in enumerate(procs):
        try:
            out, _ = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            raise RuntimeError(f"multiprocess run timed out after {timeout}s (proc {i})")
        outputs.append(out)
        if proc.returncode != 0:
            failed.append((i, proc.returncode, out))
    if failed:
        report = "\n".join(f"--- proc {i} rc={rc} ---\n{out[-4000:]}" for i, rc, out in failed)
        raise AssertionError(f"{len(failed)}/{num_processes} processes failed:\n{report}")
    return outputs


def memory_allocated_mb() -> float:
    """Best-effort live-buffer accounting on the default backend."""
    import jax

    total = 0
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
            total += stats.get("bytes_in_use", 0)
        except Exception:
            pass
    return total / 1e6
