"""Capability gating + helpers (reference ``test_utils/testing.py:83-616``:
``get_backend``, ``require_*`` decorators, ``slow``)."""

from __future__ import annotations

import functools
import os
import unittest

from ..utils.imports import is_pallas_available, is_tpu_available


def get_backend() -> tuple[str, int]:
    """(platform, device_count) — device-agnostic probe (reference
    ``testing.py:83-108`` returns (device, count, memory-fn))."""
    import jax

    return jax.default_backend(), jax.device_count()


def skip(reason: str):
    return unittest.skip(reason)


def _require(flag: bool, reason: str):
    def deco(fn):
        return unittest.skipUnless(flag, reason)(fn)

    return deco


def require_tpu(fn):
    return _require(is_tpu_available(), "test requires a TPU backend")(fn)


def require_cpu(fn):
    import jax

    return _require(jax.default_backend() == "cpu", "test requires CPU backend")(fn)


def require_single_device(fn):
    import jax

    return _require(jax.device_count() == 1, "test requires exactly 1 device")(fn)


def require_multi_device(fn):
    import jax

    return _require(jax.device_count() > 1, "test requires multiple devices")(fn)


def require_pallas(fn):
    return _require(is_pallas_available(), "test requires pallas (TPU backend)")(fn)


def slow(fn):
    """Gated behind RUN_SLOW=1 (reference ``testing.py`` ``slow``)."""
    run_slow = os.environ.get("RUN_SLOW", "0").lower() in ("1", "true", "yes")
    return unittest.skipUnless(run_slow, "slow test — set RUN_SLOW=1")(fn)


def assert_allclose_tree(a, b, rtol: float = 1e-5, atol: float = 1e-6, err_msg: str = ""):
    """Tree-wise ``np.testing.assert_allclose``."""
    import jax
    import numpy as np

    la, treedef_a = jax.tree_util.tree_flatten(a)
    lb, treedef_b = jax.tree_util.tree_flatten(b)
    assert treedef_a == treedef_b, f"tree structure mismatch: {treedef_a} vs {treedef_b}"
    for xa, xb in zip(la, lb):
        np.testing.assert_allclose(np.asarray(xa), np.asarray(xb), rtol=rtol, atol=atol,
                                   err_msg=err_msg)


def memory_allocated_mb() -> float:
    """Best-effort live-buffer accounting on the default backend."""
    import jax

    total = 0
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
            total += stats.get("bytes_in_use", 0)
        except Exception:
            pass
    return total / 1e6
