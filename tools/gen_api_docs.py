"""Generate docs/package_reference/*.md from the live package.

Mirrors the reference's ``docs/source/package_reference/`` file set (15 pages:
accelerator, state, big_modeling, cli, deepspeed, fp8, fsdp, inference,
kwargs, launchers, logging, megatron_lm, torch_wrappers, tracking, utilities)
but the content is INTROSPECTED from this package — signatures and first
docstring paragraphs — so the reference pages can never drift from the code.
``tests/test_docs.py`` regenerates into a temp dir and asserts zero diff.

Run:  python tools/gen_api_docs.py [outdir]
"""

from __future__ import annotations

import importlib
import inspect
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # introspection must not touch the TPU tunnel


# page -> (title, intro, [(module, [names] | None=all public)])
PAGES: "dict[str, tuple[str, str, list]]" = {
    "accelerator": (
        "Accelerator",
        "The central orchestration facade (reference `accelerator.py:183`): "
        "prepare assigns shardings, the hot path is one jitted train step.",
        [("accelerate_tpu.accelerator", ["Accelerator", "StepProfiler", "RemovableHandle"])],
    ),
    "state": (
        "State singletons",
        "Process/mesh state (reference `state.py`): PartialState boots "
        "`jax.distributed`, AcceleratorState owns the mesh, GradientState "
        "tracks accumulation.",
        [("accelerate_tpu.state", ["PartialState", "AcceleratorState", "GradientState"])],
    ),
    "big_modeling": (
        "Big-model inference",
        "Zero-RAM init, device maps, dispatch and offload "
        "(reference `big_modeling.py`).",
        [("accelerate_tpu.big_modeling", None), ("accelerate_tpu.hooks", None)],
    ),
    "cli": (
        "CLI",
        "`accelerate-tpu {config,launch,env,estimate-memory,merge-weights,"
        "test,tpu-config,to-fsdp2}` (reference `commands/`). Each command "
        "module exposes `main`/`*_command` entry points.",
        [("accelerate_tpu.commands.launch", ["launch_command", "build_launch_env"]),
         ("accelerate_tpu.commands.config", ["write_basic_config", "ClusterConfig"]),
         ("accelerate_tpu.commands.estimate", None),
         ("accelerate_tpu.commands.merge", None),
         ("accelerate_tpu.commands.to_fsdp2", ["to_fsdp2_command"])],
    ),
    "deepspeed": (
        "DeepSpeed (shim)",
        "There is no DeepSpeed engine on TPU: the plugin maps ZeRO staging "
        "onto GSPMD sharding (see `docs/concept_guides/fsdp_gspmd.md`).",
        [("accelerate_tpu.utils.dataclasses",
          ["DeepSpeedPlugin", "HfDeepSpeedConfig", "DummyOptim", "DummyScheduler",
           "get_active_deepspeed_plugin", "deepspeed_required"])],
    ),
    "fp8": (
        "FP8",
        "Native delayed-scaling fp8 over XLA's fp8 `dot_general` "
        "(reference delegates to TE/torchao/MS-AMP CUDA).",
        [("accelerate_tpu.ops.fp8", None),
         ("accelerate_tpu.utils.dataclasses",
          ["FP8RecipeKwargs", "TERecipeKwargs", "AORecipeKwargs", "MSAMPRecipeKwargs"])],
    ),
    "fsdp": (
        "FSDP",
        "FSDP is a NamedSharding assignment over the `dp_shard` mesh axis; "
        "the FSDP1/FSDP2 split collapses under GSPMD. Every spec decision "
        "flows through ONE `make_sharding_plan` entry point (ISSUE 9); the "
        "fused bucketed ZeRO-1 weight update lives in "
        "`parallel.weight_update`.",
        [("accelerate_tpu.utils.dataclasses", ["FullyShardedDataParallelPlugin"]),
         ("accelerate_tpu.parallel.sharding", None),
         ("accelerate_tpu.parallel.weight_update", None),
         ("accelerate_tpu.sharded_checkpoint", None)],
    ),
    "inference": (
        "Inference",
        "KV-cache generation and pipeline-parallel inference "
        "(reference `inference.py` PiPPy route). Concurrent-request serving "
        "lives in `accelerate_tpu.serving` (see the serving page).",
        [("accelerate_tpu.generation", None),
         ("accelerate_tpu.parallel.pipeline", None)],
    ),
    "serving": (
        "Serving",
        "Continuous batching over a paged KV cache (no reference "
        "counterpart): step-granular admission into running decode batches, "
        "fixed-size KV blocks in one preallocated pool with a host-side "
        "allocator, watermark/LIFO preemption with persisted resume, and a "
        "static bucket lattice so admission churn never recompiles — "
        "with automatic prefix caching (content-addressed refcounted block "
        "sharing + copy-on-write) and Pallas paged-attention decode + "
        "chunked-prefill kernels on TPU — replicated behind a health-checked "
        "router with token-exact failover, deadlines, and graceful overload "
        "shedding. Speculative decoding (a truncated-layer self-draft with "
        "bitwise-accept verification) emits multiple tokens per step without "
        "changing a single output token. "
        "The fleet can be split into disaggregated prefill/decode tiers "
        "(content-addressed KV handoff, bitwise parity with the monolith) "
        "with SLO-burn-driven autoscaling and warm pre-shipped scale-up. "
        "See `docs/serving.md` for the guide and `benchmarks/serving/` "
        "(`make bench-serve`) for the continuous-vs-static, replicated, "
        "shared-prefix, disaggregated and speculative-decoding benchmarks.",
        [("accelerate_tpu.serving.engine", ["ServingEngine", "paged_forward"]),
         ("accelerate_tpu.serving.kv_pager",
          ["BlockAllocator", "BlockAllocatorError", "BlockPoolExhausted",
           "PrefixPlan", "PrefixAllocation", "init_block_pool",
           "paged_attention"]),
         ("accelerate_tpu.ops.flash_attention",
          ["paged_attention", "paged_attention_decode",
           "paged_attention_prefill", "paged_kernel_mode"]),
         ("accelerate_tpu.models.transformer",
          ["draft_config", "draft_params"]),
         ("accelerate_tpu.serving.scheduler",
          ["Request", "RequestStatus", "Scheduler", "SchedulingError"]),
         ("accelerate_tpu.serving.buckets", ["BucketLattice"]),
         ("accelerate_tpu.serving.router",
          ["ServingRouter", "RouterRequest", "RouterRequestStatus"]),
         ("accelerate_tpu.serving.replica",
          ["ReplicaSpec", "ReplicaState", "LocalReplica", "ProcessReplica"]),
         ("accelerate_tpu.serving.admission",
          ["AdmissionController", "AdmissionVerdict", "TokenBucket"]),
         ("accelerate_tpu.serving.disagg",
          ["PrefillEngine", "DecodeEngine", "DisaggRouter", "KVHandoff",
           "KVTransport", "LocalBlockCopyTransport"]),
         ("accelerate_tpu.serving.autoscaler",
          ["AutoscalerPolicy", "lattice_fns"]),
         ("accelerate_tpu.serving.canary",
          ["CanaryGolden", "CanaryProbe", "precompute_goldens"])],
    ),
    "analysis": (
        "Static analysis (jaxlint)",
        "AST-based analyzer for jit-traced code (no reference counterpart): "
        "discovers the jit/pjit/shard_map call graph and flags host syncs "
        "(R1), recompile hazards (R2), donation bugs (R3), rank-divergent "
        "collectives (R4) and trace-time nondeterminism (R5). CLI: "
        "`python -m accelerate_tpu.analysis lint` / `make lint`. See "
        "`docs/static_analysis.md` for the rule catalog.",
        [("accelerate_tpu.analysis.engine", ["run_lint", "LintResult"]),
         ("accelerate_tpu.analysis.findings", ["Finding", "Severity", "summarize"]),
         ("accelerate_tpu.analysis.callgraph",
          ["build_package_index", "discover_traced", "PackageIndex", "ModuleIndex",
           "FunctionInfo", "JitSpec", "TracedRegion"]),
         ("accelerate_tpu.analysis.rules", ["Rule", "RuleContext", "load_all_rules"]),
         ("accelerate_tpu.analysis.baseline",
          ["load_baseline", "apply_baseline", "write_baseline", "discover_baseline"]),
         ("accelerate_tpu.analysis.reporters", ["render_human", "render_json"])],
    ),
    "checkpointing": (
        "Checkpointing",
        "Crash-consistent (staging + fsync + `_COMMITTED` marker + atomic "
        "rename) save/load with an async zero-stall path: "
        "`save_state(blocking=False)` pays only the device→host snapshot; a "
        "background writer serializes and commits (see `docs/checkpointing.md`).",
        [("accelerate_tpu.checkpointing",
          ["CheckpointCorruptError", "CheckpointSnapshot", "snapshot_accelerator_state",
           "write_snapshot", "commit_snapshot", "write_and_commit",
           "save_accelerator_state", "load_accelerator_state", "find_latest_checkpoint",
           "is_committed_checkpoint", "rotate_checkpoints", "repair_interrupted_commit",
           "save_model", "load_checkpoint_in_model"]),
         ("accelerate_tpu.checkpoint_async", ["CheckpointManager"]),
         ("accelerate_tpu.utils.dataclasses", ["CheckpointConfig"])],
    ),
    "kwargs": (
        "Kwargs handlers and plugins",
        "Configuration dataclasses (reference `utils/dataclasses.py`).",
        [("accelerate_tpu.utils.dataclasses", None)],
    ),
    "launchers": (
        "Launchers",
        "Notebook/debug launchers (reference `launchers.py`).",
        [("accelerate_tpu.launchers", None)],
    ),
    "logging": (
        "Logging",
        "Rank-aware logging (reference `logging.py`).",
        [("accelerate_tpu.logging", None)],
    ),
    "megatron_lm": (
        "Megatron-LM (shim)",
        "The Megatron engine is not ported; its TP/PP/EP degrees map onto the "
        "native mesh. Engine internals are excluded with reasons in "
        "`accelerate_tpu.utils.api_boundary.EXCLUDED_REFERENCE_UTILS`.",
        [("accelerate_tpu.utils.dataclasses", ["MegatronLMPlugin"]),
         ("accelerate_tpu.parallelism_config", ["ParallelismConfig"])],
    ),
    "torch_wrappers": (
        "Training-object wrappers and the torch bridge",
        "Data loader / optimizer / scheduler wrappers (reference "
        "`data_loader.py`, `optimizer.py`, `scheduler.py`) and the "
        "torch.export→JAX bridge that runs torch models on the TPU path.",
        [("accelerate_tpu.data_loader", None),
         ("accelerate_tpu.optimizer", None),
         ("accelerate_tpu.scheduler", None),
         ("accelerate_tpu.bridge.module", ["BridgedModule", "BridgedOutput"])],
    ),
    "telemetry": (
        "Telemetry",
        "Built-in observability (no reference counterpart): structured step "
        "events, recompile/memory/comms metrics, performance attribution "
        "(MFU/roofline cost capture + profiler trace windows), hang/crash "
        "forensics (flight recorder + watchdog), and the "
        "`python -m accelerate_tpu.telemetry report` CLI. See "
        "`docs/telemetry.md`, `docs/performance.md` and "
        "`docs/troubleshooting.md` for the guides.",
        [("accelerate_tpu.telemetry.events",
          ["EventLog", "enable", "disable", "maybe_enable_from_env", "is_enabled",
           "get_event_log", "emit", "counter", "gauge", "span", "set_step",
           "hard_flush"]),
         ("accelerate_tpu.telemetry.step_profiler",
          ["StepTelemetry", "RecompileWatcher", "install_compile_listener",
           "compile_snapshot", "record_data_wait"]),
         ("accelerate_tpu.telemetry.memory", None),
         ("accelerate_tpu.telemetry.perf",
          ["HardwarePeaks", "CompiledCost", "peaks_for_device", "device_peak_flops",
           "device_hbm_bandwidth", "train_flops_per_sample", "lm_train_mfu", "mfu",
           "arithmetic_intensity", "roofline_bucket", "capture_enabled",
           "cost_from_compiled", "capture_compiled"]),
         ("accelerate_tpu.telemetry.xplane",
          ["TraceWindows", "parse_xspace", "parse_chrome_trace", "find_trace_files",
           "summarize_planes", "summarize_trace", "is_collective_op", "is_infra_event"]),
         ("accelerate_tpu.telemetry.flight_recorder",
          ["FlightRecorder", "get_recorder", "record", "phase", "set_step",
           "current_phases", "dump", "install", "uninstall", "enabled_from_env",
           "load_flight_records"]),
         ("accelerate_tpu.telemetry.watchdog",
          ["Watchdog", "start", "stop", "maybe_start_from_env", "get_watchdog",
           "beat", "register", "unregister", "env_timeout"]),
         ("accelerate_tpu.telemetry.tracing",
          ["TraceContext", "arm", "disarm", "maybe_arm_from_env", "is_armed",
           "new_trace", "span_open", "span_close", "make_span", "emit_spans",
           "finish_trace", "spans_by_trace", "validate_span_tree",
           "chrome_trace", "format_timeline"]),
         ("accelerate_tpu.telemetry.metrics",
          ["Counter", "Gauge", "Histogram", "MetricsRegistry", "percentile",
           "quantile_from_buckets", "hist_dist", "enable", "disable",
           "maybe_enable_from_env", "inc", "set_gauge", "observe",
           "snapshot_now", "maybe_snapshot", "serve", "server_port",
           "stop_server", "parse_prometheus_text", "histogram_from_scrape"]),
         ("accelerate_tpu.telemetry.slo",
          ["SLObjective", "SLOMonitor", "serving_slos",
           "step_latency_slo_from_env", "restart_downtime_slo_from_env"]),
         ("accelerate_tpu.telemetry.goodput",
          ["build_ledger", "verdict_line", "restart_stats", "note",
           "note_step", "note_serving_step", "maybe_emit", "emit_now"]),
         ("accelerate_tpu.telemetry.regress",
          ["MetricSpec", "register", "spec_for", "load_payload", "fingerprint",
           "comparable", "extract_metrics", "compare_metrics", "scan_dir",
           "run_regress"]),
         ("accelerate_tpu.telemetry.report",
          ["build_report", "build_report_from_events", "format_report",
           "format_rank_section", "format_serving_section",
           "format_router_section", "format_slo_section",
           "format_goodput_section", "format_anomaly_section",
           "format_canary_section", "render_request",
           "find_request_trace", "load_events", "run_doctor", "main"]),
         ("accelerate_tpu.telemetry.hub",
          ["FileTail", "FleetModel", "EventHub", "render_top", "run_top",
           "run_follow"]),
         ("accelerate_tpu.telemetry.anomaly",
          ["EwmaDetector", "TrendDetector", "AnomalyEngine"]),
         ("accelerate_tpu.telemetry.tracker_bridge", None)],
    ),
    "compile_cache": (
        "Compile cache",
        "Zero-cold-start recovery (no reference counterpart): a crash-safe "
        "persistent cache of serialized AOT executables, content-addressed on "
        "(StableHLO fingerprint, mesh axes, device kind, jax/jaxlib/XLA "
        "versions, compile flags), committed with the staged-fsync-CRC-"
        "manifest-rename protocol and read defensively (corrupt/mismatched "
        "entries are quarantined and fall back to a fresh compile). Probed by "
        "the Accelerator on restart generations >= 1, loaded wholesale by the "
        "serving engine's warmup, pre-touched by the elastic supervisor, and "
        "pre-shipped to autoscaler joiners for warm (zero-compile) scale-up. "
        "See `docs/compile_cache.md`.",
        [("accelerate_tpu.compile_cache.cache",
          ["CacheKey", "CompileCache", "LoadResult", "StoreResult",
           "key_from_lowered", "environment_fingerprint", "compile_flags"]),
         ("accelerate_tpu.compile_cache.runtime",
          ["cache_enabled", "configured_cache_dir", "get_cache", "aot_compile",
           "maybe_load_executable", "maybe_export", "call_with_fallback",
           "pretouch", "preship"])],
    ),
    "resilience": (
        "Resilience",
        "Elastic preemption-tolerant training (no reference counterpart): the "
        "`accelerate-tpu launch --elastic` supervisor (exit-code "
        "classification, heartbeat-file gaps, bounded-backoff auto-resume, "
        "poison-step diagnosis), cohort membership across restarts, "
        "cross-topology checkpoint re-sharding, and the deterministic chaos "
        "harness behind `make chaos`. See `docs/resilience.md`.",
        [("accelerate_tpu.resilience.supervisor",
          ["RestartPolicy", "Supervisor", "classify_exit", "supervise_command"]),
         ("accelerate_tpu.resilience.membership",
          ["CohortSpec", "MembershipError", "negotiate_membership",
           "announce_membership", "read_roster", "publish_cohort_spec",
           "load_cohort_spec", "await_roster", "current_generation"]),
         ("accelerate_tpu.resilience.reshard",
          ["check_topology", "topology_matches", "is_elastic_compatible",
           "mesh_shape_dict", "saved_topology", "describe_shapes"]),
         ("accelerate_tpu.resilience.chaos",
          ["ChaosSchedule", "Fault", "ChaosFaultError", "arm",
           "maybe_arm_from_env", "maybe_inject", "replan_data_assignment"])],
    ),
    "tracking": (
        "Experiment tracking",
        "Tracker abstraction + integrations (reference `tracking.py`).",
        [("accelerate_tpu.tracking", None)],
    ),
    "utilities": (
        "Utilities",
        "Collectives, modeling utils, memory, offload, environment "
        "(reference `utils/`). The full reference-name boundary lives in "
        "`accelerate_tpu/utils/api_boundary.py`.",
        [("accelerate_tpu.utils.operations", None),
         ("accelerate_tpu.utils.modeling", None),
         ("accelerate_tpu.utils.memory", None),
         ("accelerate_tpu.utils.offload", None),
         ("accelerate_tpu.utils.environment", None),
         ("accelerate_tpu.utils.random", None),
         ("accelerate_tpu.utils.other", None)],
    ),
}


def _first_paragraph(obj) -> str:
    doc = inspect.getdoc(obj) or ""
    para = doc.split("\n\n", 1)[0].strip()
    return " ".join(para.split())


def _signature(obj) -> str:
    import re

    try:
        sig = str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"
    # function/object default reprs embed memory addresses — nondeterministic
    # across runs, which would make the freshness test flap
    return re.sub(r" at 0x[0-9a-f]+", "", sig)


def _public_names(mod) -> list:
    names = getattr(mod, "__all__", None)
    if names is None:
        names = [n for n, v in vars(mod).items()
                 if not n.startswith("_") and getattr(v, "__module__", None) == mod.__name__
                 and (inspect.isclass(v) or inspect.isfunction(v))]
    return names


def _render_entry(name: str, obj) -> list:
    lines = []
    if inspect.isclass(obj):
        lines.append(f"### `class {name}{_signature(obj)}`\n")
        para = _first_paragraph(obj)
        if para:
            lines.append(para + "\n")
        methods = [
            (mn, mv) for mn, mv in vars(obj).items()
            if not mn.startswith("_")
            and (inspect.isfunction(mv) or isinstance(mv, (property, classmethod, staticmethod)))
        ]
        for mn, mv in methods:
            if isinstance(mv, property):
                lines.append(f"- **`{mn}`** (property) — {_first_paragraph(mv.fget) or ''}")
            elif isinstance(mv, (classmethod, staticmethod)):
                kind = "classmethod" if isinstance(mv, classmethod) else "staticmethod"
                fn = mv.__func__
                lines.append(
                    f"- **`{mn}{_signature(fn)}`** ({kind}) — {_first_paragraph(fn) or ''}"
                )
            else:
                lines.append(f"- **`{mn}{_signature(mv)}`** — {_first_paragraph(mv) or ''}")
        if methods:
            lines.append("")
    elif inspect.isfunction(obj):
        lines.append(f"### `{name}{_signature(obj)}`\n")
        para = _first_paragraph(obj)
        if para:
            lines.append(para + "\n")
    else:
        lines.append(f"### `{name}`\n")
    return lines


def render_page(page: str) -> str:
    title, intro, sections = PAGES[page]
    out = [
        "<!-- GENERATED by tools/gen_api_docs.py — edit docstrings, not this file;",
        "     tests/test_docs.py fails when this page is stale. -->",
        f"# {title}\n",
        intro + "\n",
    ]
    for module_name, names in sections:
        mod = importlib.import_module(module_name)
        out.append(f"## `{module_name}`\n")
        mod_doc = _first_paragraph(mod)
        if mod_doc:
            out.append(mod_doc + "\n")
        for name in names or _public_names(mod):
            obj = getattr(mod, name, None)
            if obj is None:
                raise SystemExit(f"{module_name} has no attribute {name!r}")
            out.extend(_render_entry(name, obj))
    return "\n".join(out).rstrip() + "\n"


def main(outdir: str) -> None:
    os.makedirs(outdir, exist_ok=True)
    for page in sorted(PAGES):
        path = os.path.join(outdir, f"{page}.md")
        with open(path, "w") as f:
            f.write(render_page(page))
        print(f"wrote {path}")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else os.path.join(REPO, "docs", "package_reference"))
