"""Background TPU-availability watcher (round-5, VERDICT item 2).

The axon TPU tunnel has been observed down for 5+ hour stretches (round-4
postmortem). The end-of-round driver bench is one-shot: if the tunnel happens
to be down at that moment, the round records a CPU-degraded stand-in no matter
how much perf work landed. This watcher closes that gap:

- probes the TPU backend every ``--interval`` seconds in a KILLABLE subprocess
  (an in-process hang inside backend init cannot be interrupted — the C call
  never returns to the interpreter);
- the moment the chip answers, runs the FULL ``bench.py`` and caches its last
  TPU JSON line at ``BENCH_TPU_CACHE.json`` (atomic replace);
- keeps the cache fresh by re-running when it is older than ``--refresh``
  seconds and the chip is still up.

``bench.py`` prefers this cache over a CPU-degraded fallback (clearly labelled
``cached: true`` with its age), so a mid-round measurement survives an
end-of-round outage.

Run it detached for the whole round:

    nohup python tools/tpu_watcher.py >/tmp/tpu_watcher.log 2>&1 &
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CACHE = os.path.join(REPO, "BENCH_TPU_CACHE.json")

sys.path.insert(0, REPO)
# shared with bench.py: the watcher and bench's re-exec path must agree both on
# what counts as a usable live TPU line (non-degraded, non-cached) and on what
# counts as the backend being up
from bench import _pick_tpu_json_line as pick_tpu_line  # noqa: E402
from bench import _probe_backend_subprocess  # noqa: E402


def log(msg: str) -> None:
    print(f"[tpu_watcher {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def probe(timeout: int) -> bool:
    """True iff `jax.devices()` answers with a real backend within timeout.
    A hung probe is killed AND leaves a flight-record post-mortem (the probe
    arms the telemetry watchdog before touching the backend, see
    bench._probe_forensics_code); its path is logged here so a 5-hour outage
    finally comes with stacks attached."""
    ok, detail = _probe_backend_subprocess(timeout)
    if not ok:
        log(f"probe diagnosis: {detail}")
    return ok


def run_bench(bench_budget: int) -> dict | None:
    env = dict(
        os.environ,
        ACCELERATE_BENCH_RETRIES="2",
        ACCELERATE_BENCH_BUDGET=str(bench_budget),
    )
    # capture a profiler trace of the headline's hot dispatch while we have
    # the chip (VERDICT r04 item 3: a documented MFU claim needs a trace in
    # the repo); bench wraps exactly one timed dispatch in jax.profiler.
    # Captured to a staging dir and swapped in only on SUCCESS, so a bench
    # that dies mid-run (the tunnel's signature failure mode) cannot destroy
    # the last good trace; only the latest capture is kept (multi-MB each).
    trace_staging = None
    if "ACCELERATE_BENCH_TRACE" not in env:
        trace_staging = os.path.join(REPO, "traces", ".staging")
        import shutil

        shutil.rmtree(trace_staging, ignore_errors=True)
        env["ACCELERATE_BENCH_TRACE"] = trace_staging
    try:
        res = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=bench_budget + 300, env=env,
        )
        stdout = res.stdout or ""
    except subprocess.TimeoutExpired as e:
        stdout = e.stdout if isinstance(e.stdout, str) else (
            e.stdout.decode(errors="replace") if e.stdout else "")
        log(f"bench hung past {bench_budget + 300}s; mining partial output")
    parsed = pick_tpu_line(stdout)
    if trace_staging is not None:
        import shutil

        final_dir = os.path.join(REPO, "traces", "watcher")
        if parsed is not None and os.path.isdir(trace_staging) and os.listdir(trace_staging):
            shutil.rmtree(final_dir, ignore_errors=True)
            os.replace(trace_staging, final_dir)
            parsed["trace_dir"] = final_dir
        else:
            shutil.rmtree(trace_staging, ignore_errors=True)
    return parsed


def cache_age() -> float:
    try:
        return time.time() - os.path.getmtime(CACHE)
    except OSError:
        return float("inf")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--interval", type=int, default=600, help="probe period (s)")
    ap.add_argument("--refresh", type=int, default=5400,
                    help="re-measure when the cache is older than this (s)")
    ap.add_argument("--probe-timeout", type=int, default=240)
    ap.add_argument("--bench-budget", type=int, default=2400)
    ap.add_argument("--once", action="store_true",
                    help="single probe+bench attempt, then exit")
    args = ap.parse_args()

    while True:
        try:
            if cache_age() > args.refresh:
                log("probing TPU backend...")
                if probe(args.probe_timeout):
                    log("TPU up: running full bench")
                    parsed = run_bench(args.bench_budget)
                    if parsed is not None:
                        # age stamp lives INSIDE the JSON: file mtime resets
                        # on a fresh checkout, so bench's staleness check must
                        # not rely on it (a previous round's cache would look
                        # newborn)
                        parsed["measured_at_unix"] = time.time()
                        tmp = CACHE + ".tmp"
                        with open(tmp, "w") as f:
                            json.dump(parsed, f)
                        os.replace(tmp, CACHE)
                        log(f"cached TPU result: value={parsed.get('value')} "
                            f"mfu={parsed.get('mfu')}")
                    else:
                        log("bench produced no usable TPU line")
                else:
                    log("TPU probe failed/hung")
            else:
                log(f"cache fresh ({cache_age() / 60:.0f} min old); sleeping")
        except Exception as e:
            # the watcher is the round's measurement insurance: one bad cycle
            # (disk hiccup, weird subprocess error) must not kill the loop
            log(f"cycle error ({type(e).__name__}: {e}); continuing")
        if args.once:
            break
        time.sleep(args.interval)


if __name__ == "__main__":
    main()
