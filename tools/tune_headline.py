"""Headline MFU tuning grid (VERDICT r04 item 3: spend measured headroom,
target MFU >= 0.6 on the bert-base headline).

Runs the EXACT headline workload (bert-base, seq 128, bf16, loop-fused train
steps — same methodology as bench.py's run_bench) over a grid of the knobs
that plausibly move MXU utilization: global batch size, scan-vs-unrolled
layers, and steps-per-dispatch. Prints one JSON line per cell as it lands
(kill-safe) and a final summary line with the best cell.

Run on a reachable TPU:  python tools/tune_headline.py
CPU smoke (tiny model):  JAX_PLATFORMS=cpu python tools/tune_headline.py --smoke
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "examples"))

from accelerate_tpu.telemetry.perf import (  # noqa: E402
    device_peak_flops,
    train_flops_per_sample,
)


def measure_cell(batch_size: int, unroll: bool, steps_per_call: int, smoke: bool):
    import jax
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator, DataLoader
    from accelerate_tpu.models import BertConfig, bert_loss, bert_shard_rules, init_bert
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils.operations import stack_batches
    from nlp_example import DictDataset, make_synthetic_mrpc

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()

    seq_len = 128
    base = BertConfig.tiny() if smoke else BertConfig.base()
    config = dataclasses.replace(base, max_seq_len=seq_len, unroll_layers=unroll)
    accelerator = Accelerator(mixed_precision="bf16", rng_seed=0)
    n_chips = len(jax.devices())
    data = make_synthetic_mrpc(batch_size * n_chips * 4, seq_len, config.vocab_size, seed=0)
    params = init_bert(config, jax.random.PRNGKey(0))
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))
    params, opt, dl = accelerator.prepare(
        params, optax.adamw(2e-5),
        DataLoader(DictDataset(data), batch_size=batch_size),
        shard_rules=bert_shard_rules(),
    )
    batches = list(dl)
    # the ASSEMBLED global batch (bench.py:628 does the same): on a dp mesh it
    # is batch_size x dp rows — using the nominal bs would underreport by dp
    global_batch = batches[0]["labels"].shape[0]
    stacked = stack_batches([batches[i % len(batches)] for i in range(steps_per_call)])
    loop = accelerator.prepare_train_loop(lambda p, b: bert_loss(p, b, config), opt)
    opt_state = opt.opt_state
    t0 = time.time()
    params, opt_state, m = loop(params, opt_state, stacked)  # compile
    float(np.asarray(m["loss"][-1]))
    compile_s = time.time() - t0
    params, opt_state, m = loop(params, opt_state, stacked)  # warm
    float(np.asarray(m["loss"][-1]))
    n_calls = 3
    t0 = time.time()
    for _ in range(n_calls):
        params, opt_state, m = loop(params, opt_state, stacked)
    float(np.asarray(m["loss"][-1]))
    elapsed = time.time() - t0
    per_chip = n_calls * steps_per_call * global_batch / elapsed / n_chips
    peak = device_peak_flops(jax.devices()[0])
    mfu = per_chip * train_flops_per_sample(config, seq_len, n_params) / peak if peak else None
    return {
        "batch_size": batch_size, "unroll_layers": unroll,
        "steps_per_call": steps_per_call,
        "samples_per_sec_per_chip": round(per_chip, 2),
        "mfu": round(mfu, 4) if mfu else None,
        "compile_seconds": round(compile_s, 1),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny model (CPU plumbing check)")
    ap.add_argument("--budget", type=int, default=1800, help="wall-clock budget (s)")
    args = ap.parse_args()
    if args.smoke:
        import jax

        jax.config.update("jax_platforms", "cpu")
        grid = [(16, True, 10), (16, False, 10)]
    elif __import__("bench")._init_backend() != "tpu":
        # hang-proof: a dead tunnel must fail fast with output, not block
        # inside backend init (bench.py:108-115) — the probe runs in a
        # killable subprocess and falls back degraded
        print(json.dumps({"error": "TPU unreachable (degraded); tuning needs the chip"}),
              flush=True)
        return
    else:
        # bs ladder x scan-vs-unroll x dispatch fusion depth; ordered so the
        # most promising cells (unrolled, large batch) land first if the
        # budget runs out
        grid = [
            (256, True, 10), (512, True, 10), (128, True, 10),
            (256, True, 20),
            (256, False, 10), (512, False, 10),
        ]
    t_end = time.time() + args.budget
    results = []
    for bs, unroll, spc in grid:
        if time.time() > t_end - 120:
            print(json.dumps({"skipped": [bs, unroll, spc], "reason": "budget"}), flush=True)
            continue
        try:
            cell = measure_cell(bs, unroll, spc, args.smoke)
        except Exception as e:
            cell = {"batch_size": bs, "unroll_layers": unroll, "steps_per_call": spc,
                    "error": f"{type(e).__name__}: {str(e)[:200]}"}
        print(json.dumps(cell), flush=True)
        results.append(cell)
    ok = [c for c in results if c.get("samples_per_sec_per_chip")]
    if ok:
        best = max(ok, key=lambda c: c["samples_per_sec_per_chip"])
        print(json.dumps({"best": best, "cells_measured": len(ok)}), flush=True)


if __name__ == "__main__":
    main()
