"""Benchmark: BERT-base MRPC-shaped training throughput (samples/sec/chip).

The driver's north-star metric (BASELINE.json): ``nlp_example.py`` (BERT-base,
seq 128) training samples/sec/chip. Runs on whatever the default JAX backend is
(the real TPU chip under the driver; CPU elsewhere with a tiny model), times the
jitted train step after compilation, and prints ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.

``vs_baseline`` anchors to ``BENCH_BASELINE.json`` (written on first TPU run) so
round-over-round regressions are visible; the reference repo publishes no number
for this metric (BASELINE.md).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def run_bench():
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import Accelerator, DataLoader
    from accelerate_tpu.models import BertConfig, bert_loss, bert_shard_rules, init_bert

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        config = BertConfig.base()
        batch_size = 64
        steps = 30
    else:
        config = BertConfig.tiny()
        batch_size = 16
        steps = 10
    import dataclasses

    seq_len = 128
    config = dataclasses.replace(config, max_seq_len=seq_len)

    accelerator = Accelerator(mixed_precision="bf16", rng_seed=0)
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "examples"))
    from nlp_example import DictDataset, make_synthetic_mrpc

    n_chips = len(jax.devices())
    data = make_synthetic_mrpc(batch_size * n_chips * 4, seq_len, config.vocab_size, seed=0)
    params = init_bert(config, jax.random.PRNGKey(0))
    params, opt, dl = accelerator.prepare(
        params,
        optax.adamw(2e-5),
        DataLoader(DictDataset(data), batch_size=batch_size),
        shard_rules=bert_shard_rules(),
    )
    step = accelerator.prepare_train_step(lambda p, b: bert_loss(p, b, config), opt)
    opt_state = opt.opt_state

    batches = list(dl)
    global_batch = batches[0]["labels"].shape[0]
    # compile (value fetch, not block_until_ready: remote-tunneled TPU backends
    # can report ready before execution completes — a host transfer cannot lie)
    params, opt_state, m = step(params, opt_state, batches[0])
    float(np.asarray(m["loss"]))
    t0 = time.time()
    for i in range(steps):
        params, opt_state, m = step(params, opt_state, batches[i % len(batches)])
    float(np.asarray(m["loss"]))
    elapsed = time.time() - t0
    samples_per_sec = steps * global_batch / elapsed
    per_chip = samples_per_sec / n_chips
    return {
        "samples_per_sec": samples_per_sec,
        "per_chip": per_chip,
        "backend": jax.default_backend(),
        "n_chips": n_chips,
        "model": "bert-base" if on_tpu else "bert-tiny",
        "final_loss": float(m["loss"]),
    }


def main():
    result = run_bench()
    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_BASELINE.json")
    vs_baseline = 1.0
    if result["backend"] == "tpu":
        if os.path.exists(baseline_path):
            with open(baseline_path) as f:
                baseline = json.load(f)
            if baseline.get("per_chip"):
                vs_baseline = result["per_chip"] / baseline["per_chip"]
        else:
            with open(baseline_path, "w") as f:
                json.dump({"per_chip": result["per_chip"], "model": result["model"]}, f)
    print(
        json.dumps(
            {
                "metric": f"{result['model']} mrpc-shaped train throughput ({result['backend']}, bf16)",
                "value": round(result["per_chip"], 2),
                "unit": "samples/sec/chip",
                "vs_baseline": round(vs_baseline, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
