"""Benchmark: BERT-base MRPC-shaped training throughput (samples/sec/chip) + MFU.

The driver's north-star metric (BASELINE.json): ``nlp_example.py`` (BERT-base,
seq 128) training samples/sec/chip. Runs on whatever the default JAX backend is
(the real TPU chip under the driver; CPU elsewhere with a tiny model), times the
jitted train step after compilation, and emits cumulative JSON lines to stdout:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "mfu": ...}`` —
one after the headline and one after each completed breadth config, each a
superset of the previous (intermediate lines carry ``"partial": true``). **The
LAST parseable line is the record**; emitting incrementally means a driver
`timeout` kill can no longer erase the round's data (round-4 postmortem:
``parsed: null``).

Hardening (round 1-4 postmortems):
- backend init is probed in killable subprocesses with bounded retry, all under
  one wall-clock budget (``ACCELERATE_BENCH_BUDGET``, default 25 min — inside
  the driver's observed ~30 min timeout); probing can never starve measurement;
- any terminal failure still prints a structured JSON line (with an "error"
  key) so the driver's record is parseable either way;
- if the TPU tunnel is down at bench time, a mid-round measurement cached by
  ``tools/tpu_watcher.py`` is preferred over a CPU-degraded stand-in.

``vs_baseline`` anchors to ``BENCH_BASELINE.json`` (written on first TPU run) so
round-over-round regressions are visible; the reference repo publishes no number
for this metric (BASELINE.md).

**Staleness contract for consumers** (see benchmarks/README.md "Reading
cached records"): a record with ``"cached": true`` is a REAL TPU measurement
taken mid-round by ``tools/tpu_watcher.py`` up to
``ACCELERATE_BENCH_CACHE_MAX_AGE_MIN`` (default 720) minutes BEFORE bench
time — it predates any code change landed since ``measured_at_unix`` and its
``value``/``vs_baseline`` must not be read as a measurement of the current
tree. Consumers parsing only ``value``/``vs_baseline`` MUST also check
``cached`` (and ``cache_age_minutes``) before treating the number as current.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

# Hardware peaks + MFU methodology live in ONE place — the telemetry perf
# registry — so bench and the telemetry layer can never disagree on what a
# chip's peak FLOP/s is (ISSUE 7; the old private _PEAK_FLOPS table is gone).
from accelerate_tpu.telemetry.perf import (
    cost_from_compiled,
    device_hbm_bandwidth,
    device_peak_flops,
    lm_train_mfu,
    train_flops_per_sample,
)

import contextlib
import itertools
import signal
from typing import Optional


def _env_int(key: str, default: int) -> int:
    """int(os.environ[key]) with the default on missing OR malformed values —
    a bad knob must never cost the round its number."""
    try:
        return int(os.environ.get(key, default))
    except (TypeError, ValueError):
        print(f"WARNING: ignoring malformed {key}={os.environ.get(key)!r}", file=sys.stderr)
        return default


# ---- wall-clock budget (round-4 postmortem: the probe ladder alone outlived
# the driver's ~30 min `timeout` and the run was killed with ZERO output).
# Everything in this file is budgeted against one deadline; when it nears,
# remaining work is skipped with a note instead of being killed mid-flight.
_T0 = time.time()
_BUDGET = _env_int("ACCELERATE_BENCH_BUDGET", 1500)  # 25 min < driver's ~30


def _remaining() -> float:
    return _BUDGET - (time.time() - _T0)


def _emit(payload: dict) -> None:
    """Print one parseable JSON line to stdout NOW (flush: a driver `timeout`
    kill must not take buffered output with it). Called after the headline and
    again after every completed config with the cumulative result, so however
    the process dies, the last line standing carries everything measured so
    far — the round can no longer end with `parsed: null`."""
    print(json.dumps(sanitize_json(payload)), flush=True)


@contextlib.contextmanager
def _deadline(seconds: int):
    """Hard wall-clock limit for a blocking call (the axon tunnel has been
    observed to HANG inside backend init, not just error)."""

    def _raise(signum, frame):
        raise TimeoutError(f"backend init exceeded {seconds}s")

    old = signal.signal(signal.SIGALRM, _raise)
    signal.alarm(seconds)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


# killed/hung probes leave their post-mortem here (flight-rank0.json with the
# probe's ring buffer + all-thread stacks, see telemetry/flight_recorder.py).
# Each probe attempt writes its own attempt-<pid>-<n> subdir so a retry (or a
# concurrent tpu_watcher probe) never clobbers evidence already linked in
# _FLIGHT_RECORDS.
_PROBE_FLIGHT_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "telemetry", "probe"
)
_FLIGHT_RECORDS: list = []  # artifact paths, surfaced in the output JSON
_PROBE_ATTEMPT = itertools.count()


def _probe_forensics_code(flight_dir: str, watchdog_timeout: float,
                          init_stmt: str = "import jax; jax.devices()") -> str:
    """Probe program with the forensics layer armed BEFORE backend init: the
    watchdog dumps a flight record (naming the ``backend_init`` phase, with
    all-thread stacks) and aborts well before the parent's kill deadline — so
    "hung past 150s (killed)" finally comes with evidence attached."""
    repo = os.path.dirname(os.path.abspath(__file__))
    # interval pinned to timeout/8 so the faulthandler dead-man (fires at
    # timeout + 4*interval = 1.5x) lands inside the parent's kill window even
    # for short probes — the observed axon hang holds the GIL inside
    # initialize_pjrt_plugin, so the C-level dumper is the artifact that lands
    return (
        "import sys\n"
        f"sys.path.insert(0, {repo!r})\n"
        "from accelerate_tpu.telemetry import flight_recorder, watchdog\n"
        f"flight_recorder.install(out_dir={flight_dir!r})\n"
        f"watchdog.start(timeout={watchdog_timeout!r}, "
        f"interval={watchdog_timeout / 8.0!r}, abort_on_stall=True, "
        f"out_dir={flight_dir!r})\n"
        "with flight_recorder.phase('backend_init', op='jax.devices'):\n"
        f"    {init_stmt}\n"
        "print('ok')\n"
    )


def _probe_flight_artifact(flight_dir: str) -> Optional[str]:
    """Best evidence a killed probe left: the flight JSON when the watchdog
    thread got to run, else the faulthandler dead-man stacks (a GIL-holding C
    hang — the axon-tunnel case — starves every Python thread, and only the
    C-level dumper fires)."""
    path = os.path.join(flight_dir, "flight-rank0.json")
    if os.path.exists(path):
        return path
    for name in ("watchdog-rank0.stacks", "crash-rank0.stacks"):
        path = os.path.join(flight_dir, name)
        if os.path.exists(path) and os.path.getsize(path) > 0:
            return path
    return None


def _probe_backend_subprocess(timeout: int, init_stmt: Optional[str] = None) -> "tuple[bool, str]":
    """Probe backend init in a KILLABLE subprocess. A hung tunnel blocks inside
    a C call that never returns to the interpreter, so an in-process SIGALRM
    handler never runs (observed: bench hung >60 min past its 180 s deadline);
    a subprocess can always be killed from outside. Returns ``(ok, detail)``
    where detail carries the probe's stderr tail so a degraded round records
    WHY (round-3 postmortem: the JSON said only "failed/hung") — and, when the
    probe hung, the path of the flight-record post-mortem its in-process
    watchdog dumped before the kill."""
    import shutil
    import subprocess

    # per-attempt dir: a stale artifact from a previous probe can't masquerade
    # as this one's, and a retry can't destroy evidence a previous attempt
    # already linked in _FLIGHT_RECORDS
    flight_dir = os.path.join(
        _PROBE_FLIGHT_DIR, f"attempt-{os.getpid()}-{next(_PROBE_ATTEMPT)}"
    )
    shutil.rmtree(flight_dir, ignore_errors=True)
    code = _probe_forensics_code(
        flight_dir,
        # dump+abort comfortably inside the parent's kill window (observed
        # inits answer in seconds or hang forever; 0.6x keeps slow-but-live
        # inits alive while the dump still lands well before the kill)
        watchdog_timeout=max(1.0, timeout * 0.6),
        **({"init_stmt": init_stmt} if init_stmt else {}),
    )

    def _with_flight(detail: str) -> str:
        artifact = _probe_flight_artifact(flight_dir)
        if artifact:
            _FLIGHT_RECORDS.append(artifact)
            if "flight record:" not in detail:  # stderr tail may already name it
                return f"{detail}; flight record: {artifact}"
        return detail

    try:
        res = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True, timeout=timeout
        )
        if res.returncode == 0 and "ok" in res.stdout:
            shutil.rmtree(flight_dir, ignore_errors=True)  # healthy probes leave no litter
            return True, "ok"
        tail = (res.stderr or res.stdout or "").strip().splitlines()[-3:]
        return False, _with_flight(
            f"rc={res.returncode}: " + " | ".join(t.strip() for t in tail)[-300:]
        )
    except subprocess.TimeoutExpired:
        return False, _with_flight(f"hung past {timeout}s (killed)")


_BACKEND_DEGRADED: Optional[str] = None  # set when TPU probe failed -> CPU run
_PROBE_HISTORY: list = []  # per-attempt failure details for the output JSON
# probe phase is hard-capped (~5.5 min default): round-4's 8x180s ladder +
# ~6 min of sleeps outlived the DRIVER's own timeout and the round recorded
# nothing. A dead tunnel now costs minutes, then the CPU fallback runs and
# emits incrementally; a mid-round recovery is caught by the watcher cache
# and the end-of-round re-exec instead of by probe patience.


def _init_backend(
    retries: Optional[int] = None, delay: float = 20.0, init_timeout: Optional[int] = None
) -> str:
    """``jax.default_backend()`` with bounded retry: a remote-tunneled TPU
    backend can be transiently UNAVAILABLE (or hang); probe in a subprocess
    first (see :func:`_probe_backend_subprocess`), clear the backend cache and
    back off between tries. ``ACCELERATE_BENCH_RETRIES`` /
    ``ACCELERATE_BENCH_PROBE_TIMEOUT`` / ``ACCELERATE_BENCH_PROBE_BUDGET``
    override the patience; the whole phase additionally respects the global
    ``ACCELERATE_BENCH_BUDGET`` deadline so probing can never starve the
    measurement phase of its wall-clock."""
    import jax

    global _BACKEND_DEGRADED, _PROBE_HISTORY
    if retries is None:
        retries = _env_int("ACCELERATE_BENCH_RETRIES", 2)
    retries = max(retries, 1)  # 0 would skip probing entirely, last_err=None
    if init_timeout is None:
        init_timeout = _env_int("ACCELERATE_BENCH_PROBE_TIMEOUT", 150)
    probe_deadline = time.time() + min(
        _env_int("ACCELERATE_BENCH_PROBE_BUDGET", 330), max(_remaining() - 120, 60)
    )

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # explicit CPU request: the axon sitecustomize ignores the env var, so
        # apply it through jax.config (which wins) and skip the TPU probe
        jax.config.update("jax_platforms", "cpu")
        return jax.default_backend()

    last_err = None
    for attempt in range(retries):
        probe_left = int(probe_deadline - time.time())
        if probe_left < 20:
            last_err = last_err or TimeoutError("probe budget exhausted")
            _PROBE_HISTORY.append("probe budget exhausted before attempt "
                                  f"{attempt + 1}/{retries}")
            break
        ok, detail = _probe_backend_subprocess(min(init_timeout, probe_left))
        if not ok:
            last_err = TimeoutError(f"backend probe: {detail}")
            _PROBE_HISTORY.append(detail)
            print(
                f"bench probe {attempt + 1}/{retries} failed: {detail}", file=sys.stderr
            )
            if attempt + 1 < retries:
                time.sleep(min(delay, max(probe_deadline - time.time(), 0)))
            continue
        try:
            with _deadline(init_timeout):
                return jax.default_backend()
        except (RuntimeError, TimeoutError) as e:  # backend init failure/hang
            last_err = e
            _PROBE_HISTORY.append(f"in-process init: {type(e).__name__}: {e}")
            try:
                jax._src.xla_bridge._clear_backends()
            except Exception:
                pass
            if attempt + 1 < retries:
                time.sleep(min(delay, max(probe_deadline - time.time(), 0)))
    # last resort: a CPU number is better than no number — but mark it degraded
    try:
        jax.config.update("jax_platforms", "cpu")
        backend = jax.default_backend()
        _BACKEND_DEGRADED = f"TPU init failed after {retries} probes: {last_err}"
        print(f"WARNING: {_BACKEND_DEGRADED}; falling back to cpu", file=sys.stderr)
        return backend
    except Exception:
        raise last_err


def _compiled_step_cost(jitted_step, *args):
    """``(CompiledCost, aot_executable)`` from XLA's cost analysis (counts
    what actually runs, remat recompute included — hardware utilization, not
    model-MFU; see telemetry/perf.py). The AOT executable is returned so the
    caller can run it directly instead of paying a second trace/compile
    through the jit cache. ``(None, None)`` when the backend doesn't report
    costs."""
    try:
        compiled = jitted_step.lower(*args).compile()
        return cost_from_compiled("bench_step", compiled), compiled
    except Exception as e:
        print(f"cost_analysis unavailable: {type(e).__name__}: {e}", file=sys.stderr)
        return None, None


def _first_working_step(candidates, make_step, params, opt_state, batch, label):
    """Compile-and-warm the first candidate config that runs: returns
    ``(step, chosen, params, opt_state)`` with the warm-up step's outputs
    committed. Failed candidates print to stderr and the next is tried;
    exhausting the ladder re-raises the last error."""
    last_err = None
    for cand in candidates:
        try:
            step = make_step(cand)
            params_c, opt_state_c, loss = step(params, opt_state, batch)
            float(np.asarray(loss))  # force execution (tunnel-safe sync)
            return step, cand, params_c, opt_state_c
        except Exception as e:
            last_err = e
            print(f"{label} candidate {cand!r} failed "
                  f"({type(e).__name__}: {str(e)[:200]}); trying next", file=sys.stderr)
    raise RuntimeError(f"no {label} candidate compiled") from last_err


def _reset_state():
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def run_bench_resnet(on_tpu: bool) -> dict:
    """Config #2 (BASELINE: cv_example ResNet-50 DP): single-chip image
    throughput, ResNet-50 @192² on TPU / tiny convnet-scale on CPU."""
    import time as _t

    import jax
    import numpy as np
    import optax

    from accelerate_tpu.models.resnet import ResNetConfig, init_resnet, resnet_loss

    _reset_state()
    if on_tpu:
        config, bs, side, steps = ResNetConfig.resnet50(num_classes=1000), 64, 192, 20
    else:
        config, bs, side, steps = ResNetConfig.tiny(), 8, 32, 3
    params = init_resnet(config, jax.random.PRNGKey(0))
    import jax.numpy as jnp

    params = jax.tree_util.tree_map(lambda x: x.astype(jnp.bfloat16), params)
    opt = optax.sgd(0.1, momentum=0.9)
    opt_state = opt.init(params)
    rng = np.random.default_rng(0)
    batch = {
        "pixels": jnp.asarray(rng.normal(size=(bs, side, side, 3)).astype(np.float32), jnp.bfloat16),
        "labels": jnp.asarray(rng.integers(0, config.num_classes, (bs,)), jnp.int32),
    }

    @jax.jit
    def step(p, s, b):
        loss, grads = jax.value_and_grad(lambda p: resnet_loss(p, b, config))(p)
        updates, s = opt.update(grads, s, p)
        return optax.apply_updates(p, updates), s, loss

    # XLA's own per-step FLOP count (convs dominate; no analytic formula
    # needed) → hardware utilization for the per-config MFU table. The AOT
    # executable is reused as the hot-loop runner so the FLOP count costs no
    # second compilation; skipped entirely where no peak is known (CPU).
    step_cost = None
    if device_peak_flops(jax.devices()[0]):
        step_cost, aot = _compiled_step_cost(step, params, opt_state, batch)
        if aot is not None:
            step = aot
    params, opt_state, loss = step(params, opt_state, batch)
    float(np.asarray(loss))
    t0 = _t.time()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, batch)
    final = float(np.asarray(loss))
    elapsed = _t.time() - t0
    out = {
        "metric": "resnet50 image-train throughput" if on_tpu else "resnet-tiny train throughput",
        "value": round(steps * bs / elapsed, 2),
        "unit": "images/sec/chip",
        "image_side": side,
        "final_loss": round(final, 4),
    }
    peak = device_peak_flops(jax.devices()[0])
    if peak and step_cost:
        out["mfu"] = round(step_cost.flops * steps / elapsed / peak, 4)
        # XLA reports bytes too: place the conv-dominated step on the roofline
        if step_cost.intensity is not None:
            out["arithmetic_intensity"] = round(step_cost.intensity, 2)
            out["roofline"] = step_cost.roofline
    return out


def run_bench_fsdp_lm(on_tpu: bool) -> dict:
    """Config #4 (BASELINE: GPT-2-large 774M FSDP fine-tune): single-chip LM
    train step at 774M-param scale with remat; the multi-chip FSDP path is
    validated by dryrun_multichip (no multi-chip hardware here)."""
    import time as _t

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu.models import LlamaConfig, init_llama
    from accelerate_tpu.models.transformer import llama_loss

    _reset_state()
    if on_tpu:
        # ≈ GPT-2-large scale: 774M params
        config = LlamaConfig(vocab_size=50257, dim=1280, n_layers=36, n_heads=20,
                             n_kv_heads=20, max_seq_len=512, unroll_layers=False)
        bs, seq, steps = 8, 512, 10
    else:
        config = LlamaConfig.tiny()
        bs, seq, steps = 2, 64, 2
    params = init_llama(config, jax.random.PRNGKey(0))
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))
    params = jax.tree_util.tree_map(lambda x: x.astype(jnp.bfloat16), params)
    opt = optax.adafactor(1e-4)  # sharded-friendly second-moment factoring
    opt_state = opt.init(params)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, config.vocab_size, (bs, seq)), jnp.int32
    )

    def make_step(remat):
        @jax.jit
        def step(p, s, b):
            loss, grads = jax.value_and_grad(
                lambda p: llama_loss(p, b, config, remat=remat)
            )(p)
            updates, s = opt.update(grads, s, p)
            return optax.apply_updates(p, updates), s, loss

        return step

    batch = {"input_ids": ids}
    # policy ladder: "dots_no_batch" keeps projection outputs (less recompute,
    # more HBM) and falls back to full recompute if this model/chip combination
    # can't hold them — the bench self-tunes instead of hard-coding the trade
    step, remat_used, params, opt_state = _first_working_step(
        ("dots_no_batch", True) if on_tpu else (True,),
        make_step, params, opt_state, batch, label="fsdp_lm remat",
    )
    t0 = _t.time()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, batch)
    final = float(np.asarray(loss))
    elapsed = _t.time() - t0
    tokens_per_sec = steps * bs * seq / elapsed
    out = {
        "metric": "lm-774M fsdp-scale train throughput" if on_tpu else "lm-tiny train throughput",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec/chip",
        "n_params": n_params,
        "final_loss": round(final, 4),
        "remat": str(remat_used),
    }
    mfu = lm_train_mfu(tokens_per_sec, n_params, config, seq)
    if mfu is not None:
        out["mfu"] = mfu  # model FLOPs only; remat recompute not counted
    return out


def run_bench_grad_accum(on_tpu: bool) -> dict:
    """Config #3 (BASELINE: by_feature/gradient_accumulation.py + bf16):
    BERT with 4-step MultiSteps accumulation, timed with the SAME methodology
    as the headline (micro-steps fused 12-per-dispatch via
    ``prepare_train_loop``) so the number isolates the accumulation
    boundary's cost rather than dispatch latency."""
    import time as _t

    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import BertConfig, bert_loss, bert_shard_rules, init_bert
    from accelerate_tpu.utils.operations import stack_batches

    _reset_state()
    import dataclasses

    seq_len = 128
    if on_tpu:
        config = dataclasses.replace(BertConfig.base(), max_seq_len=seq_len)
        # micro-batch 64 = the headline's proven rung: the config isolates the
        # accumulation boundary's cost, so it should otherwise match the
        # headline's utilization, not run starved at bs16
        micro_bs, accum, n_calls = 64, 4, 4
    else:
        config = dataclasses.replace(BertConfig.tiny(), max_seq_len=seq_len)
        micro_bs, accum, n_calls = 4, 4, 2
    steps_per_call = 12  # 3 full accumulation cycles per dispatch
    accelerator = Accelerator(
        mixed_precision="bf16", gradient_accumulation_steps=accum, rng_seed=0
    )
    params = init_bert(config, jax.random.PRNGKey(0))
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))
    params, opt = accelerator.prepare(
        params, optax.adamw(2e-5), shard_rules=bert_shard_rules()
    )
    rng = np.random.default_rng(0)

    def micro_batch(seed):
        r = np.random.default_rng(seed)
        return {
            "input_ids": jnp.asarray(r.integers(0, config.vocab_size, (micro_bs, seq_len)), jnp.int32),
            "attention_mask": jnp.ones((micro_bs, seq_len), jnp.int32),
            "token_type_ids": jnp.zeros((micro_bs, seq_len), jnp.int32),
            "labels": jnp.asarray(r.integers(0, 2, (micro_bs,)), jnp.int32),
        }

    stacked = stack_batches([micro_batch(i) for i in range(steps_per_call)])
    loop = accelerator.prepare_train_loop(lambda p, b: bert_loss(p, b, config), opt)
    opt_state = opt.opt_state
    params, opt_state, m = loop(params, opt_state, stacked)  # compile
    float(np.asarray(m["loss"][-1]))
    params, opt_state, m = loop(params, opt_state, stacked)  # warm
    float(np.asarray(m["loss"][-1]))
    t0 = _t.time()
    for _ in range(n_calls):
        params, opt_state, m = loop(params, opt_state, stacked)
    final = float(np.asarray(m["loss"][-1]))
    elapsed = _t.time() - t0
    n_chips = len(jax.devices())
    samples = n_calls * steps_per_call * micro_bs
    out = {
        "metric": f"bert grad-accum x{accum} train throughput (bf16, loop-fused)",
        "value": round(samples / elapsed / n_chips, 2),
        "unit": "samples/sec/chip",
        "micro_batch": micro_bs,
        "accum_steps": accum,
        "final_loss": round(final, 4),
    }
    # same model-FLOPs methodology as the headline, via the shared helper
    mfu = lm_train_mfu(samples / elapsed / n_chips * seq_len, n_params, config, seq_len)
    if mfu is not None:
        out["mfu"] = mfu
    return out


def run_bench_inference(on_tpu: bool) -> dict:
    """Config #5 (BASELINE: big-model-inference Llama dispatch generate):
    load seconds + seconds/token, the reference's benchmark table columns
    (``benchmarks/big_model_inference/README.md:27-37``)."""
    import time as _t

    import jax
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu.generation import greedy_generate
    from accelerate_tpu.models import LlamaConfig, init_llama

    _reset_state()
    if on_tpu:
        config = LlamaConfig(vocab_size=32000, dim=2048, n_layers=16, n_heads=32,
                             n_kv_heads=8, max_seq_len=512)
        bs, prompt_len, new_tokens = 8, 128, 64
    else:
        config = LlamaConfig.tiny()
        bs, prompt_len, new_tokens = 2, 16, 8
    t0 = _t.time()
    params = init_llama(config, jax.random.PRNGKey(0))
    params = jax.device_put(
        jax.tree_util.tree_map(lambda x: x.astype(jnp.bfloat16), params), jax.devices()[0]
    )
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))
    load_s = _t.time() - t0
    prompt = np.random.default_rng(0).integers(0, config.vocab_size, (bs, prompt_len)).astype(np.int32)
    _, stats = greedy_generate(
        params, prompt, config, max_new_tokens=new_tokens, return_stats=True, warmup=True
    )
    out = {
        "metric": "llama-1B kv-cache generate" if on_tpu else "llama-tiny kv-cache generate",
        "value": round(stats["decode_tokens_per_sec"], 1),
        "unit": "tokens/sec",
        "n_params": n_params,
        "load_seconds": round(load_s, 2),
        "seconds_per_token": round(stats["seconds_per_token"], 4),
        "batch": bs,
    }
    peak = device_peak_flops(jax.devices()[0])
    if peak:
        # decode is HBM-bandwidth-bound: 2N model FLOPs/token gives a LOW MFU
        # by design — the informative per-config number is how far from the
        # bandwidth roof the decode sits, so both are reported
        out["mfu"] = round(stats["decode_tokens_per_sec"] * 2 * n_params / peak, 4)
        hbm_bw = device_hbm_bandwidth(jax.devices()[0])
        if hbm_bw:
            # weights (bf16, 2N bytes) are read once per decode STEP; all batch
            # rows share that read, so steps/sec = tokens_per_sec / batch
            out["hbm_roofline_frac"] = round(
                (stats["decode_tokens_per_sec"] / bs) * (2.0 * n_params) / hbm_bw, 4
            )
    # CPU-OFFLOAD leg: the reference table's actual subject (its GPU rows are
    # offload-bound: OPT-30B fp16 cpu-offload = 2.37 s/token). Per-layer paged
    # decode with one-ahead prefetch; optional under the global budget.
    if _remaining() > 180:
        try:
            from accelerate_tpu.big_modeling import cpu_offload
            from accelerate_tpu.generation import generate_dispatched, unstack_layer_params

            off_tokens = min(new_tokens, 16)
            with _deadline(int(max(_remaining() - 90, 60))):
                # the D2H transfer of the whole param tree is python-level and
                # tunnel-bound — it must sit INSIDE the deadline too
                dp = cpu_offload(unstack_layer_params(params, config))
                _, off_stats = generate_dispatched(
                    dp, prompt, config, max_new_tokens=off_tokens,
                    return_stats=True, warmup=True,
                )
            out["cpu_offload_tokens_per_sec"] = round(off_stats["decode_tokens_per_sec"], 1)
            out["cpu_offload_seconds_per_token"] = round(off_stats["seconds_per_token"], 4)
        except Exception as e:
            out["cpu_offload_error"] = f"{type(e).__name__}: {str(e)[:200]}"
    return out


def run_bench():
    import jax
    import optax

    from accelerate_tpu import Accelerator, DataLoader
    from accelerate_tpu.models import BertConfig, bert_loss, bert_shard_rules, init_bert

    backend = _init_backend()
    on_tpu = backend == "tpu"
    if on_tpu:
        config = BertConfig.base()
        # ladder: larger global batches raise MXU utilization (VERDICT r03:
        # MFU 0.544 @ bs64 — the chip has headroom); first size that
        # compiles+runs wins, OOM degrades to the next. 512 added round 5:
        # bert-base @ S=128 activations fit comfortably in 16 GB HBM
        batch_sizes = [512, 256, 128, 64]
        steps = 30
    else:
        config = BertConfig.tiny()
        batch_sizes = [16]
        steps = 10
    import dataclasses

    seq_len = 128
    config = dataclasses.replace(config, max_seq_len=seq_len)
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "examples"))
    from nlp_example import DictDataset, make_synthetic_mrpc

    from accelerate_tpu.utils.operations import stack_batches

    n_chips = len(jax.devices())

    def run_at(batch_size: int):
        _reset_state()
        accelerator = Accelerator(mixed_precision="bf16", rng_seed=0)
        data = make_synthetic_mrpc(batch_size * n_chips * 4, seq_len, config.vocab_size, seed=0)
        params = init_bert(config, jax.random.PRNGKey(0))
        n_params = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))
        params, opt, dl = accelerator.prepare(
            params,
            optax.adamw(2e-5),
            DataLoader(DictDataset(data), batch_size=batch_size),
            shard_rules=bert_shard_rules(),
        )
        opt_state = opt.opt_state
        batches = list(dl)
        global_batch = batches[0]["labels"].shape[0]
        # The hot loop runs through prepare_train_loop: K steps scanned inside
        # ONE jitted dispatch, so per-step host/dispatch latency (≈9 ms/step
        # through a remote-tunneled runtime) is amortized away. Parity with the
        # per-step path is pinned by
        # tests/test_accelerator.py::test_train_loop_matches_per_step_calls.
        steps_per_call = 10
        stacked = stack_batches([batches[i % len(batches)] for i in range(steps_per_call)])
        loop = accelerator.prepare_train_loop(lambda p, b: bert_loss(p, b, config), opt)
        n_calls = max(1, steps // steps_per_call)
        # compile (value fetch, not block_until_ready: remote-tunneled TPU
        # backends can report ready before execution completes — a host
        # transfer cannot lie)
        params, opt_state, m = loop(params, opt_state, stacked)
        float(np.asarray(m["loss"][-1]))
        # one warm pass: the first post-compile dispatch carries one-time
        # runtime setup (~25% on the tunneled runtime), not steady-state
        params, opt_state, m = loop(params, opt_state, stacked)
        float(np.asarray(m["loss"][-1]))
        # optional profiler capture (VERDICT r04 item 2: trace-verified
        # kernel engagement): ACCELERATE_BENCH_TRACE=<dir> wraps ONE timed
        # dispatch in jax.profiler so the claimed hot path is inspectable
        trace_dir = os.environ.get("ACCELERATE_BENCH_TRACE", "").strip() or None
        if trace_dir:
            jax.profiler.start_trace(trace_dir)
            try:
                params, opt_state, m = loop(params, opt_state, stacked)
                float(np.asarray(m["loss"][-1]))
            finally:
                # a failure mid-trace must not leave the profiler running — the
                # next ladder attempt's start_trace would fail
                jax.profiler.stop_trace()
        t0 = time.time()
        for _ in range(n_calls):
            params, opt_state, m = loop(params, opt_state, stacked)
        final_loss = float(np.asarray(m["loss"][-1]))
        elapsed = time.time() - t0
        samples_per_sec = n_calls * steps_per_call * global_batch / elapsed
        return samples_per_sec, final_loss, n_params, trace_dir

    last_msg = None
    for batch_size in batch_sizes:
        try:
            samples_per_sec, final_loss, n_params, trace_dir = run_at(batch_size)
            break
        except Exception as e:  # OOM at this size: degrade down the ladder
            # keep only the MESSAGE: holding the exception would pin the OOM'd
            # attempt's device buffers alive (via __traceback__ frame locals)
            # through the next, smaller attempt
            last_msg = f"{type(e).__name__}: {str(e)[:300]}"
            print(f"headline bs={batch_size} failed ({last_msg}); trying next",
                  file=sys.stderr)
    else:
        raise RuntimeError(f"no headline batch size ran (last: {last_msg})")
    per_chip = samples_per_sec / n_chips

    peak = device_peak_flops(jax.devices()[0])
    mfu = (
        per_chip * train_flops_per_sample(config, seq_len, n_params) / peak if peak else None
    )
    trace_summary = None
    if trace_dir:
        # the captured trace is parsed, not just linked: top-k kernel/fusion
        # durations, the compute/collective/idle split and the comms-overlap
        # ratio ride the round's payload (telemetry/xplane.py parser)
        try:
            from accelerate_tpu.telemetry.xplane import summarize_trace

            ts = summarize_trace(trace_dir, top_k=5)
            trace_summary = {
                key: ts[key]
                for key in ("compute_s", "collective_s", "idle_s", "comms_overlap_ratio")
            }
            trace_summary["top_ops"] = ts["top_ops"]
        except Exception as e:
            print(f"trace summary unavailable: {type(e).__name__}: {e}", file=sys.stderr)
    return {
        "samples_per_sec": samples_per_sec,
        "per_chip": per_chip,
        "backend": backend,
        "n_chips": n_chips,
        "model": "bert-base" if on_tpu else "bert-tiny",
        "batch_size": batch_size,
        "final_loss": final_loss,
        "mfu": mfu,
        "n_params": n_params,
        "device_kind": getattr(jax.devices()[0], "device_kind", "unknown"),
        **({"trace_dir": trace_dir} if trace_dir else {}),
        **({"trace_summary": trace_summary} if trace_summary else {}),
    }


def run_bench_weight_update(on_tpu: bool) -> dict:
    """Fused ZeRO-1 weight-update config (ISSUE 9): fused-vs-annotation step
    time, per-replica optimizer-state footprint, and the PR 7 comms-overlap
    ratio over the fused step's armed trace windows. On TPU it runs in-process
    on the real chips (a subprocess could not share the exclusive TPU); on CPU
    it delegates to a subprocess so the 8-virtual-device mesh the fused path
    needs can be requested before backend init — the parent's 1-device CPU
    backend is already frozen."""
    base = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks", "weight_update", "run.py"
    )
    if on_tpu:
        import importlib.util

        spec = importlib.util.spec_from_file_location("bench_weight_update_run", base)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        out = mod.run_bench_weight_update(
            True, steps=20, dim=2048, layers=8, trace_every=8
        )
    else:
        import subprocess

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, base, "--steps", "8", "--dim", "256",
             "--layers", "2", "--trace-every", "4"],
            capture_output=True, text=True, timeout=600, env=env,
        )
        if proc.returncode != 0:
            raise RuntimeError(f"weight_update bench failed: {proc.stderr[-500:]}")
        out = json.loads(proc.stdout.strip().splitlines()[-1])
    return {
        "metric": "zero1 fused/unfused step-time ratio",
        "value": out["value"],
        "unit": out["unit"],
        "fused": out["fused"],
        "unfused": out["unfused"],
        "opt_state_fraction": out["fused"]["opt_state_fraction"],
        "overlap_ratio": out["overlap_ratio"],
        "collective_bytes_per_step": out["collective_bytes_per_step"],
        "n_devices": out["n_devices"],
    }


def run_bench_serving(on_tpu: bool) -> dict:
    """Serving config (ISSUE 11): continuous-vs-static batching ratio under a
    seeded Poisson open-loop load through the paged-KV serving engine, plus
    the continuous leg's occupancy and p50/p99 per-request latency.
    Delegates to ``benchmarks/serving/run.py`` (same engine `make
    bench-serve` runs)."""
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks", "serving", "run.py"
    )
    spec = importlib.util.spec_from_file_location("bench_serving_run", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = mod.run_bench_serving(on_tpu)
    replicated = mod.run_bench_replicated(on_tpu)
    spec_decode = mod.run_bench_spec_decode(on_tpu)
    return {
        "metric": "serving throughput ratio (continuous/static batching)",
        "value": out["value"],
        "unit": out["unit"],
        "continuous": out["continuous"],
        "static": out["static"],
        "p99_latency_ms": out["p99_latency_ms"],
        "requests": out["requests"],
        "max_slots": out["max_slots"],
        # ISSUE 12 router leg: tok/s scaling over data-parallel replicas and
        # the no-lost-requests + output-parity invariants under a replica kill
        "replicated_scaling": replicated["value"],
        "replicated": replicated["replicated"],
        "replica_kill": replicated["replica_kill"],
        "kill_outputs_match_unkilled": replicated["kill_outputs_match_unkilled"],
        # ISSUE 18 speculative-decoding leg: bitwise-accept self-draft vs the
        # plain decode loop over one workload, plus the prefill-kernel chunk
        # microbench
        "spec_decode": spec_decode,
        # regression-guarded (telemetry/regress.py flattens these under
        # configs.serving.* with the *accept_rate* / *spec_decode* /
        # *prefill_kernel* specs): accept-rate and step-reduction drops or a
        # gather-path latency regression fail `make bench-check`
        "guarded": {
            "spec_decode_accept_rate": spec_decode["spec_accept_rate"],
            "spec_decode_tokens_per_s_ratio": spec_decode["tokens_per_s_ratio"],
            "prefill_kernel_gather_us_per_token": (
                spec_decode["prefill_kernel"]["gather_us_per_token"]
            ),
        },
    }


def run_bench_attention(on_tpu: bool) -> dict:
    """Attention kernel config (ISSUE 20): fwd+bwd µs/token and
    fraction-of-roofline over the (impl × seq × dtype × sparsity) grid — the
    measurement behind ``ops.attention.ATTN_CROSSOVER_S`` — plus the
    fp8-vs-bf16 llama train-step leg. Delegates to
    ``benchmarks/attention/run.py`` (same grid ``make bench-attn`` runs)."""
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks", "attention", "run.py"
    )
    spec = importlib.util.spec_from_file_location("bench_attention_run", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.run_bench_attention(on_tpu)


def run_bench_checkpoint_stall(on_tpu: bool) -> dict:
    """Checkpoint-stall config (ISSUE 5 acceptance): exposed-stall ratio of
    async vs sync ``save_state`` around a fixed-cadence step loop — how much
    of the blocking save's step-time tax the background writer still exposes
    (< 0.20 is the bar), plus async p95 step time vs the no-checkpoint
    baseline. Delegates to ``benchmarks/checkpoint/run.py``."""
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks", "checkpoint", "run.py"
    )
    spec = importlib.util.spec_from_file_location("bench_checkpoint_run", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # the benchmark's defaults: enough compute per save window (every*compute_ms)
    # to hide a 16 MiB fsync'd write — smaller windows make the ratio noisy
    # (sync's total stall shrinks toward the async path's constant snapshot cost)
    out = mod.run_bench_checkpoint(on_tpu, steps=75, compute_ms=30.0, every=25, mb=16.0)
    return {
        "metric": "checkpoint exposed-stall ratio (async/sync)",
        "value": out["value"],
        "unit": out["unit"],
        "p95_async_over_baseline": out["p95_async_over_baseline"],
        "baseline": out["baseline"],
        "sync": out["sync"],
        "async": out["async"],
        "state_mb": out["state_mb"],
        "save_every": out["save_every"],
    }


def run_bench_longcontext(on_tpu: bool) -> dict:
    """Long-context config (reference claims: CP "1M+ seq" / ALST "15M tokens",
    ``docs/source/concept_guides/{context,sequence}_parallelism.md``; here the
    single-chip leg): decoder train step at 8k sequence with the streaming
    flash-attention kernel + remat — the per-chip building block the cp-axis
    ring attention composes over ICI (multi-chip path exercised by
    dryrun_multichip and tests/test_long_context.py)."""
    import time as _t

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu.models import LlamaConfig, init_llama
    from accelerate_tpu.models.transformer import llama_loss

    _reset_state()
    # ACCELERATE_BENCH_LONGCTX_SEQ: benchmarks/long_context/run.py --seq knob
    # for the S-sweep (VERDICT r04 item 4: prove flash wins at long S); honored
    # on CPU too so the knob plumbing is testable without a chip
    if on_tpu:
        seq = _env_int("ACCELERATE_BENCH_LONGCTX_SEQ", 8192)
        config = LlamaConfig(vocab_size=32000, dim=1024, n_layers=16, n_heads=16,
                             n_kv_heads=8, max_seq_len=seq, unroll_layers=False)
        bs, steps = 1, 8
    else:
        import dataclasses as _dc

        seq = _env_int("ACCELERATE_BENCH_LONGCTX_SEQ", 256)
        config = _dc.replace(LlamaConfig.tiny(), max_seq_len=max(seq, 256))
        bs, steps = 1, 2
    params = init_llama(config, jax.random.PRNGKey(0))
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))
    params = jax.tree_util.tree_map(lambda x: x.astype(jnp.bfloat16), params)
    opt = optax.adafactor(1e-4)
    opt_state = opt.init(params)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, config.vocab_size, (bs, seq)), jnp.int32
    )
    def make_step(impl, remat):
        @jax.jit
        def step(p, s, b):
            loss, grads = jax.value_and_grad(
                lambda p: llama_loss(p, b, config, remat=remat, attention_impl=impl)
            )(p)
            updates, s = opt.update(grads, s, p)
            return optax.apply_updates(p, updates), s, loss

        return step

    batch = {"input_ids": ids}
    # ladder: flash attention with the lighter remat policy first, degrading to
    # full recompute, then the einsum path — measure the best that runs
    ladder = (
        [("flash", "dots_no_batch"), ("flash", True), ("xla", True)]
        if on_tpu
        else [("xla", True)]
    )
    step, (impl, remat_used), params, opt_state = _first_working_step(
        ladder, lambda c: make_step(*c), params, opt_state, batch, label="long-context",
    )
    t0 = _t.time()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, batch)
    final = float(np.asarray(loss))
    elapsed = _t.time() - t0
    tokens_per_sec = steps * bs * seq / elapsed
    out = {
        "metric": f"long-context train throughput (seq {seq}, {impl} attention)",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec/chip",
        "seq_len": seq,
        "n_params": n_params,
        "final_loss": round(final, 4),
        "remat": str(remat_used),
    }
    mfu = lm_train_mfu(tokens_per_sec, n_params, config, seq)
    if mfu is not None:
        out["mfu"] = mfu  # attention FLOPs dominate at this S; remat not counted
    # flash-vs-einsum EVIDENCE (VERDICT r04 item 4): when the winner was flash
    # and the budget allows, ALSO time the einsum path at the same S so the
    # crossover claim is measured, not asserted — the docstring of the fused
    # kernel documents the short-S regime; this documents the long-S one.
    # The leg is strictly optional: it runs under its own _deadline carved out
    # of the global budget (a slow einsum compile must not take the finished
    # flash measurement down with it) and the flash params are dropped first
    # (pinning a second params+opt copy would confound an einsum OOM).
    if impl == "flash" and _remaining() > 300:
        def _time_einsum(remat_policy):
            p2, s2, l2 = alt_step(params_e, opt_state_e, batch)  # compile+warm
            float(np.asarray(l2))
            t1 = _t.time()
            for _ in range(steps):
                p2, s2, l2 = alt_step(p2, s2, batch)
            float(np.asarray(l2))
            return steps * bs * seq / (_t.time() - t1)

        params_e, opt_state_e = params, opt_state
        del params, opt_state, loss  # only the einsum copies stay live
        leg_budget = int(max(_remaining() - 120, 60))
        for alt_remat in dict.fromkeys([remat_used, True]):  # winner's policy, then full recompute
            try:
                with _deadline(leg_budget):
                    alt_step = make_step("xla", alt_remat)
                    einsum_tps = _time_einsum(alt_remat)
                out["einsum_tokens_per_sec"] = round(einsum_tps, 1)
                out["einsum_remat"] = str(alt_remat)
                out["flash_vs_einsum"] = round(tokens_per_sec / einsum_tps, 3)
                break
            except Exception as e:  # OOM/timeout: try the heavier-recompute config
                out["einsum_error"] = f"remat={alt_remat}: {type(e).__name__}: {str(e)[:200]}"
    return out


def run_bench_compile_time(on_tpu: bool) -> dict:
    """Compile-time config (reference ``benchmarks/torch.compile/README.md``:
    regional vs full compilation, 5-9x claimed on Llama-1B..13B): our
    scan-over-stacked-layers IS regional compilation — one layer body compiled
    once regardless of depth — vs ``unroll_layers=True`` which inlines every
    layer like a full torch.compile. Reports wall seconds to lower+compile the
    jitted forward both ways AND the steady-state forward step time both ways
    (regional compilation must not cost runtime), at the reference's model
    scale: 24 layers x dim 2048 (Llama-1B-class) on TPU."""
    import dataclasses
    import time as _t

    import jax
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu.models import LlamaConfig, init_llama, llama_forward

    _reset_state()
    if on_tpu:
        # Llama-1B class, the smallest row of the reference's compile table
        base = LlamaConfig(vocab_size=32000, dim=2048, n_layers=24, n_heads=16,
                           n_kv_heads=8, max_seq_len=256)
        B, S, step_iters = 1, 128, 20
    else:
        base = LlamaConfig.tiny()
        B, S, step_iters = 1, 32, 5
    ids = np.zeros((B, S), np.int32)

    # throwaway compile first: one-time backend/compiler startup (tens of
    # seconds through the TPU tunnel) must not land in the first timed region
    jax.jit(lambda x: x + 1).lower(np.float32(0)).compile()

    # real params once (bf16), shared by both variants for the step timing
    real_params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16), init_llama(base, jax.random.PRNGKey(0))
    )
    abstract_params = jax.eval_shape(lambda: real_params)

    def measure(unroll: bool, timeout_s: int):
        config = dataclasses.replace(base, unroll_layers=unroll)
        fn = jax.jit(lambda p, i: llama_forward(p, i, config, attention_impl="xla"))
        t0 = _t.time()
        try:
            with _deadline(timeout_s):
                compiled = fn.lower(abstract_params, ids).compile()
        except TimeoutError:
            return None, None  # unrolled 24-layer compile can blow the budget
        compile_s = _t.time() - t0
        out = compiled(real_params, ids)
        float(np.asarray(out).ravel()[0])  # force completion (tunnel-safe)
        t0 = _t.time()
        for _ in range(step_iters):
            out = compiled(real_params, ids)
        float(np.asarray(out).ravel()[0])
        step_ms = (_t.time() - t0) / step_iters * 1e3
        return compile_s, step_ms

    # NOTE: _deadline is SIGALRM-based and cannot interrupt a C++ XLA compile
    # mid-flight (the handler fires when the call returns); it reliably bounds
    # the remote-compile (HTTP, python-level) path this environment uses. As a
    # second line of defense the unrolled compile is SKIPPED up front when its
    # projected cost (~ scan_s x n_layers, the inlining multiplier) would
    # clearly blow the budget — better no number than a 45-minute stall.
    budget = _env_int("ACCELERATE_BENCH_COMPILE_TIMEOUT", 600)
    scan_s, scan_step_ms = measure(False, budget)   # regional: one layer body
    out = {
        "metric": "forward compile seconds (scan=regional vs unrolled=full)",
        "value": round(scan_s, 2) if scan_s is not None else 0.0,
        "unit": "seconds",
        "n_layers": base.n_layers,
        "dim": base.dim,
        "scan_step_ms": round(scan_step_ms, 2) if scan_step_ms is not None else None,
    }
    if scan_s is None:
        # 0.0 would read as a PERFECT lower-is-better result: null it instead
        out["value"] = None
        out["note"] = f"scan compile exceeded {budget}s budget (killed)"
        return out
    projected_full = scan_s * base.n_layers
    if projected_full > 2 * budget:
        out["note"] = (
            f"unrolled compile skipped: projected ~{projected_full:.0f}s "
            f"(scan {scan_s:.1f}s x {base.n_layers} layers) exceeds the {budget}s budget"
        )
        return out
    full_s, full_step_ms = measure(True, budget)    # full: every layer inlined
    if full_s is None:
        out["note"] = f"unrolled compile exceeded {budget}s budget (killed)"
    else:
        out["full_compile_seconds"] = round(full_s, 2)
        out["full_step_ms"] = round(full_step_ms, 2)
        if scan_s:
            out["compile_speedup"] = round(full_s / scan_s, 2)
    return out


def apply_baseline_anchors(result: dict, configs: dict, baseline_path: str) -> float:
    """Anchor this run against BENCH_BASELINE.json (TPU runs only).

    The headline anchors to ``per_chip``; each breadth config anchors to its
    own first nonzero TPU value, mutating its entry with a ``vs_baseline``
    ratio (note: compile_time measures seconds, so LOWER is better there).
    First sighting of any anchor writes it back. Returns the headline ratio.
    """
    baseline = {}
    if os.path.exists(baseline_path):
        try:
            with open(baseline_path) as f:
                baseline = json.load(f)
        except (json.JSONDecodeError, OSError):  # corrupt/unreadable = absent:
            baseline = {}  # re-anchor rather than die before the output line
    if not isinstance(baseline, dict):  # wrong-shaped but valid JSON: re-anchor
        baseline = {}

    def _finite(x) -> bool:
        return isinstance(x, (int, float)) and math.isfinite(x)

    vs_baseline = 1.0
    dirty = False
    if _finite(baseline.get("per_chip")) and baseline["per_chip"]:
        # non-finite headline vs a real anchor = failed run: report the 0.0
        # failure sentinel, not 1.0 "at baseline"
        vs_baseline = (
            result["per_chip"] / baseline["per_chip"] if _finite(result["per_chip"]) else 0.0
        )
        anchor_bs = baseline.get("batch_size")
        if anchor_bs is not None and result.get("batch_size") not in (None, anchor_bs):
            # the batch ladder may land on a different size than the anchor
            # run — that ratio mixes config change with real perf change
            result["vs_baseline_note"] = (
                f"batch size differs from anchor (bs{result.get('batch_size')} "
                f"vs anchor bs{anchor_bs})"
            )
    elif _finite(result["per_chip"]):
        baseline.update(
            {
                "per_chip": result["per_chip"],
                "model": result["model"],
                "batch_size": result.get("batch_size"),
            }
        )
        dirty = True
    cfg_anchor = baseline.setdefault("configs", {})
    if not isinstance(cfg_anchor, dict):
        cfg_anchor = baseline["configs"] = {}
    cfg_meta = baseline.setdefault("configs_meta", {})
    if not isinstance(cfg_meta, dict):
        cfg_meta = baseline["configs_meta"] = {}
    for name, entry in configs.items():
        raw_value = entry.get("value")
        value = raw_value or 0.0
        if _finite(cfg_anchor.get(name)) and cfg_anchor.get(name):
            if raw_value is None:
                # explicit null (e.g. compile budget blown): null ratio too —
                # 0.0 would read as "infinitely fast" for lower-is-better
                entry["vs_baseline"] = None
            else:
                entry["vs_baseline"] = round(value / cfg_anchor[name], 4) if _finite(value) else 0.0
            # self-tuning configs: a ratio against an anchor measured under a
            # DIFFERENT remat policy is not a like-for-like comparison — say so
            prev_meta = cfg_meta.get(name)
            prev_remat = prev_meta.get("remat") if isinstance(prev_meta, dict) else None
            if "remat" in entry and prev_remat is not None and prev_remat != entry["remat"]:
                entry["vs_baseline_note"] = (
                    f"remat policy differs from anchor ({prev_remat} vs {entry['remat']})"
                )
        elif _finite(value) and value:
            cfg_anchor[name] = value
            if "remat" in entry:
                cfg_meta[name] = {"remat": entry["remat"]}
            dirty = True
    if dirty:
        tmp = baseline_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(baseline, f)
        os.replace(tmp, baseline_path)  # atomic: a killed run never truncates
    return vs_baseline


def sanitize_json(obj):
    """Replace non-finite floats with None anywhere in a JSON-ish tree —
    ``json.dumps`` would otherwise emit bare ``NaN``/``Infinity`` tokens and
    break the driver's one-parseable-line contract."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: sanitize_json(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [sanitize_json(v) for v in obj]
    return obj


def _maybe_reexec_on_recovered_tpu() -> Optional[dict]:
    """End-of-round re-probe (round-3 postmortem): the CPU-degraded path takes
    minutes to run its configs — if the TPU tunnel has RECOVERED by then, a
    whole-bench re-exec gets the round a real TPU number after all. Returns the
    child's best TPU result dict on success, else None. Budget-aware (round-4
    postmortem): only attempted when enough of the global deadline is left, the
    child inherits exactly that remainder, and a timed-out child's PARTIAL
    stdout is still mined — the child emits incrementally, so a kill mid-config
    can still hand back a real TPU headline. ``ACCELERATE_BENCH_REEXEC`` guards
    against recursion."""
    import subprocess

    if os.environ.get("ACCELERATE_BENCH_REEXEC") == "1":
        return None
    budget_left = _remaining() - 90  # leave room to print the fallback line
    if budget_left < 240:  # a TPU headline needs ~3-4 min incl. compile
        _PROBE_HISTORY.append(f"re-exec skipped: only {budget_left:.0f}s of budget left")
        return None
    ok, _detail = _probe_backend_subprocess(min(120, int(budget_left // 3)))
    if not ok:
        return None
    budget_left = _remaining() - 90
    print("TPU recovered after degraded run: re-executing bench", file=sys.stderr)
    env = dict(
        os.environ,
        ACCELERATE_BENCH_REEXEC="1",
        ACCELERATE_BENCH_RETRIES="1",
        ACCELERATE_BENCH_BUDGET=str(max(int(budget_left - 60), 120)),
    )
    timeout_s = min(_env_int("ACCELERATE_BENCH_REEXEC_TIMEOUT", 3600), int(budget_left))
    try:
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
        )
        stdout, stderr = res.stdout or "", res.stderr or ""
    except subprocess.TimeoutExpired as e:
        _PROBE_HISTORY.append(f"re-exec child hung past {timeout_s}s (killed)")
        stdout = e.stdout if isinstance(e.stdout, str) else (
            e.stdout.decode(errors="replace") if e.stdout else "")
        stderr = e.stderr if isinstance(e.stderr, str) else (
            e.stderr.decode(errors="replace") if e.stderr else "")
        print(f"bench re-exec timed out after {timeout_s}s; mining partial output",
              file=sys.stderr)
    sys.stderr.write(stderr[-2000:] if stderr else "")
    return _pick_tpu_json_line(stdout)


_TPU_CACHE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_TPU_CACHE.json"
)


def _load_tpu_cache() -> Optional[dict]:
    """Mid-round TPU result cached by ``tools/tpu_watcher.py``. The axon tunnel
    has been down for 5+ hour stretches (round-4); the watcher probes all round
    and runs the full bench the moment the chip is back, so the end-of-round
    bench can fall back to a REAL measurement taken hours earlier instead of a
    CPU-degraded stand-in. The cached line is labelled as such."""
    try:
        with open(_TPU_CACHE_PATH) as f:
            cached = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(cached, dict) or cached.get("degraded"):
        return None
    if "TPU" not in str(cached.get("device_kind", "")):
        return None
    measured_at = cached.get("measured_at_unix")
    if not isinstance(measured_at, (int, float)):
        # age must come from INSIDE the JSON (watcher stamps it): file mtime
        # resets on a fresh checkout, so a previous round's committed cache
        # would look newborn. No stamp = not trustworthy = not used.
        _PROBE_HISTORY.append("watcher cache rejected: no measured_at_unix stamp")
        return None
    age_min = (time.time() - measured_at) / 60.0
    max_age_min = _env_int("ACCELERATE_BENCH_CACHE_MAX_AGE_MIN", 12 * 60)
    if not (0 <= age_min <= max_age_min):
        # a cache older than the ~12h round is a PREVIOUS round's measurement;
        # emitting it would mask this round's regressions/improvements
        _PROBE_HISTORY.append(
            f"watcher cache rejected: {age_min:.0f} min old > {max_age_min} min"
        )
        return None
    cached["cached"] = True
    cached["cache_age_minutes"] = round(age_min, 1)
    cached.pop("partial", None)  # promotion to final record: the flag means
    # "superseded by a later line", which no longer holds
    cached["note"] = (
        "TPU result measured mid-round by tools/tpu_watcher.py (tunnel was down "
        "at bench time); " + str(cached.get("note", ""))
    )
    return cached


def _pick_tpu_json_line(stdout: str) -> Optional[dict]:
    """Last stdout line that parses as a NON-degraded, NON-cached real-TPU
    bench result — only a live measurement may replace the caller's degraded
    output. ``cached: true`` lines are rejected: a child that itself degraded
    and fell back to the watcher cache would otherwise launder an hours-old
    number as freshly recovered. Shared by the re-exec path here and by
    ``tools/tpu_watcher.py``."""
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if (
            "TPU" in str(parsed.get("device_kind", ""))
            and not parsed.get("degraded")
            and not parsed.get("cached")
        ):
            return parsed
    return None


def _num(x):  # NaN/Inf would make json.dumps emit a non-parseable token
    return None if x is None or not isinstance(x, (int, float)) or not math.isfinite(x) else round(x, 4)


def _headline_payload(result: dict, vs_baseline, configs: dict, partial: bool) -> dict:
    payload = {
        "metric": f"{result['model']} mrpc-shaped train throughput ({result['backend']}, bf16)",
        "value": _num(result["per_chip"]) or 0.0,
        "unit": "samples/sec/chip",
        "vs_baseline": _num(vs_baseline) or 0.0,
        "mfu": _num(result["mfu"]),
        "device_kind": result["device_kind"],
        "n_chips": result["n_chips"],
        "batch_size": result.get("batch_size"),
        "final_loss": _num(result["final_loss"]),
        **({"trace_dir": result["trace_dir"]} if result.get("trace_dir") else {}),
        **({"trace_summary": result["trace_summary"]} if result.get("trace_summary") else {}),
        **(
            {"vs_baseline_note": result["vs_baseline_note"]}
            if result.get("vs_baseline_note")
            else {}
        ),
        # this environment has no hub access: data is synthetic
        # MRPC-shaped, so loss/accuracy are parity signals between
        # configs/rounds, not real-GLUE numbers
        "note": "synthetic data (no hub access); loss comparable across rounds only",
        **({"degraded": _BACKEND_DEGRADED} if _BACKEND_DEGRADED else {}),
        **({"probe_history": _PROBE_HISTORY[-8:]} if _PROBE_HISTORY else {}),
        **({"flight_records": sorted(set(_FLIGHT_RECORDS))} if _FLIGHT_RECORDS else {}),
        "configs": configs,  # _emit sanitizes the whole payload
    }
    try:
        # THE fingerprint helper (benchmarks/_common.py): the regression
        # sentinel refuses to compare payloads from different environments
        from benchmarks._common import env_fingerprint

        payload["env"] = env_fingerprint()
    except Exception:
        pass
    if partial:
        payload["partial"] = True  # superseded by a later cumulative line
    return payload


def _provisional_vs_baseline(result: dict, baseline_path: str) -> float:
    """Read-only headline ratio for the early incremental lines; the final line
    recomputes via :func:`apply_baseline_anchors` (which may also write).
    CPU-degraded runs report 1.0 like the final line does — a CPU-vs-TPU-anchor
    ratio would mix hardware change with perf change. The anchor cannot change
    mid-run, so it is read once and memoized."""
    if result.get("backend") != "tpu":
        return 1.0
    if "anchor" not in _provisional_vs_baseline.__dict__:
        try:
            with open(baseline_path) as f:
                _provisional_vs_baseline.anchor = json.load(f).get("per_chip")
        except (OSError, json.JSONDecodeError, AttributeError):
            _provisional_vs_baseline.anchor = None
    anchor = _provisional_vs_baseline.anchor
    if isinstance(anchor, (int, float)) and math.isfinite(anchor) and anchor:
        per_chip = result.get("per_chip")
        if isinstance(per_chip, (int, float)) and math.isfinite(per_chip):
            return per_chip / anchor
        return 0.0
    return 1.0


def main():
    try:
        result = run_bench()
    except Exception as e:  # ALWAYS print one parseable line (round-1 postmortem)
        print(
            json.dumps(
                {
                    "metric": "bert mrpc-shaped train throughput (failed)",
                    "value": 0.0,
                    "unit": "samples/sec/chip",
                    "vs_baseline": 0.0,
                    "error": f"{type(e).__name__}: {e}",
                    **({"degraded": _BACKEND_DEGRADED} if _BACKEND_DEGRADED else {}),
                    **({"probe_history": _PROBE_HISTORY[-8:]} if _PROBE_HISTORY else {}),
                    **({"flight_records": sorted(set(_FLIGHT_RECORDS))} if _FLIGHT_RECORDS else {}),
                }
            ),
            flush=True,
        )
        sys.exit(1)

    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_BASELINE.json")
    on_tpu = result["backend"] == "tpu"
    # the headline is the round's must-have number: emit it the moment it
    # exists, then re-emit cumulatively as each breadth config lands
    configs = {}
    _emit(_headline_payload(
        result, _provisional_vs_baseline(result, baseline_path), configs, partial=True
    ))
    # benchmark breadth (BASELINE configs 2/4/5): progress lines go to STDERR
    # (humans/logs); stdout carries cumulative JSON lines, the LAST of which is
    # the driver's record
    for name, fn in (
        ("resnet_dp", run_bench_resnet),
        ("grad_accum", run_bench_grad_accum),
        ("fsdp_lm", run_bench_fsdp_lm),
        ("inference", run_bench_inference),
        ("long_context", run_bench_longcontext),
        # renamed from "compile_time" when the workload moved to the
        # reference's Llama-1B scale (24L x 2048) — the old 12-layer anchor is
        # not like-for-like; a fresh anchor is seeded on the next TPU run
        ("compile_time_llama1b", run_bench_compile_time),
        ("checkpoint_stall", run_bench_checkpoint_stall),
        ("weight_update", run_bench_weight_update),
        ("serving", run_bench_serving),
        ("attention", run_bench_attention),
    ):
        if _remaining() < 120:
            configs[name] = {
                "metric": name, "value": None,
                "note": f"skipped: bench wall-clock budget exhausted ({_remaining():.0f}s left)",
            }
            continue
        try:
            entry = fn(on_tpu)
        except Exception as e:  # one config failing must not kill the rest
            entry = {"metric": name, "value": 0.0, "error": f"{type(e).__name__}: {e}"}
        print(json.dumps(sanitize_json(entry)), file=sys.stderr, flush=True)
        configs[name] = entry
        _emit(_headline_payload(
            result, _provisional_vs_baseline(result, baseline_path), configs, partial=True
        ))
    if _BACKEND_DEGRADED:
        # the CPU configs above took minutes — one more chance at a TPU number
        recovered = _maybe_reexec_on_recovered_tpu()
        if recovered is not None:
            recovered.pop("partial", None)  # now the final record, not superseded
            _emit(recovered)
            return
        cached = _load_tpu_cache()
        if cached is not None:
            # a real mid-round TPU measurement beats a live CPU stand-in
            _emit(cached)
            return
    vs_baseline = 1.0
    if on_tpu:
        vs_baseline = apply_baseline_anchors(result, configs, baseline_path)
    _emit(_headline_payload(result, vs_baseline, configs, partial=False))


if __name__ == "__main__":
    main()
