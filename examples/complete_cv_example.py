"""Complete CV example: cv_example + tracking, per-epoch checkpointing, resume,
LR scheduling (reference ``examples/complete_cv_example.py`` — ResNet-50 with
checkpointing/tracking on pet images; same training shape on synthetic data).

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/complete_cv_example.py --cpu --project-dir /tmp/cvproj \
    --checkpointing-steps epoch [--resume-from-checkpoint .../checkpoint_0]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from example_utils import DictDataset, add_common_args, make_synthetic_images, maybe_force_cpu


def training_function(args):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator, DataLoader, ProjectConfiguration

    pc = ProjectConfiguration(project_dir=args.project_dir, automatic_checkpoint_naming=True)
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        log_with="jsonl" if args.with_tracking else None,
        project_config=pc,
        rng_seed=args.seed,
        cpu=args.cpu,
    )
    if args.with_tracking:
        accelerator.init_trackers("complete_cv_example", config=vars(args))

    from cv_example import convnet_forward, init_convnet

    train = make_synthetic_images(args.train_size, size=args.image_size, seed=0)
    test = make_synthetic_images(args.eval_size, size=args.image_size, seed=1)
    params = init_convnet(jax.random.PRNGKey(args.seed))
    train_dl = DataLoader(DictDataset(train), batch_size=args.batch_size,
                          shuffle=True, seed=args.seed)
    eval_dl = DataLoader(DictDataset(test), batch_size=args.batch_size)
    steps_per_epoch = max(len(train_dl), 1)
    total = max(args.epochs * steps_per_epoch, 2)
    optimizer = optax.adamw(
        optax.warmup_cosine_decay_schedule(0.0, args.lr, max(total // 10, 1), total)
    )
    params, optimizer, train_dl, eval_dl = accelerator.prepare(
        params, optimizer, train_dl, eval_dl
    )

    def loss_fn(p, batch):
        logits = convnet_forward(p, batch["pixel_values"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, batch["labels"][:, None], axis=-1))

    step_fn = accelerator.prepare_train_step(loss_fn, optimizer)
    eval_fn = accelerator.prepare_eval_step(lambda p, b: convnet_forward(p, b["pixel_values"]))
    opt_state = optimizer.opt_state

    start_epoch = 0
    if args.resume_from_checkpoint:
        params = accelerator.load_state(args.resume_from_checkpoint, params=params)
        opt_state = accelerator._optimizers[-1].opt_state
        name = os.path.basename(os.path.normpath(args.resume_from_checkpoint))
        if name.startswith("checkpoint_"):
            start_epoch = int(name.split("_")[1]) + 1
        accelerator.print(f"resumed from {args.resume_from_checkpoint} (epoch {start_epoch})")

    acc = 0.0
    for epoch in range(start_epoch, args.epochs):
        for batch in train_dl:
            params, opt_state, metrics = step_fn(params, opt_state, batch)
        correct = total_n = 0
        for batch in eval_dl:
            preds = jnp.argmax(eval_fn(params, batch), axis=-1)
            g = accelerator.gather_for_metrics({"p": preds, "l": batch["labels"]})
            correct += int(np.sum(np.asarray(g["p"]) == np.asarray(g["l"])))
            total_n += int(np.asarray(g["l"]).shape[0])
        acc = correct / max(total_n, 1)
        accelerator.print(f"epoch {epoch}: accuracy {acc:.3f} loss {float(metrics['loss']):.4f}")
        if args.with_tracking:
            accelerator.log({"accuracy": acc, "train_loss": float(metrics["loss"])}, step=epoch)
        if args.checkpointing_steps == "epoch" and args.project_dir:
            accelerator.save_state(params=params)
    accelerator.end_training()
    return {"eval_accuracy": acc}


def main():
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--image-size", type=int, default=32)
    parser.add_argument("--project-dir", default=None)
    parser.add_argument("--with-tracking", action="store_true")
    parser.add_argument("--checkpointing-steps", default=None, choices=[None, "epoch"])
    parser.add_argument("--resume-from-checkpoint", default=None)
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)


if __name__ == "__main__":
    main()
