#!/bin/bash
#SBATCH --job-name=accelerate-tpu-pod
#SBATCH --nodes=4                  # one task per TPU-VM host
#SBATCH --ntasks-per-node=1
#SBATCH --time=04:00:00
# Multi-host SPMD launch under SLURM (reference: examples/slurm/submit_multinode.sh).
# One process per host; jax.distributed rendezvous at node 0.

export COORDINATOR=$(scontrol show hostnames "$SLURM_JOB_NODELIST" | head -n1)
export ACCELERATE_COORDINATOR_ADDRESS=${COORDINATOR}:8476
export ACCELERATE_NUM_PROCESSES=$SLURM_NNODES
export ACCELERATE_PROCESS_ID=$SLURM_PROCID

srun accelerate-tpu launch \
    --num_machines "$SLURM_NNODES" \
    --machine_rank "$SLURM_PROCID" \
    --main_process_ip "$COORDINATOR" \
    --main_process_port 8476 \
    --mixed_precision bf16 \
    --dp_shard_size "$SLURM_NNODES" \
    examples/nlp_example.py --model-size base
