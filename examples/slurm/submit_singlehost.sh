#!/bin/bash
#SBATCH --job-name=accelerate-tpu
#SBATCH --nodes=1
#SBATCH --time=02:00:00
# Single-host launch with elastic restart supervision
# (reference: examples/slurm/submit_multigpu.sh).

accelerate-tpu launch \
    --mixed_precision bf16 \
    --max_restarts 2 \
    examples/complete_nlp_example.py --checkpointing_steps epoch
