"""Shared bits for the example scripts (synthetic datasets with the reference
examples' tensor shapes — no network egress in CI)."""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class DictDataset:
    def __init__(self, data: dict):
        self.data = data

    def __len__(self):
        return len(next(iter(self.data.values())))

    def __getitem__(self, i):
        return {k: v[i] for k, v in self.data.items()}


def make_synthetic_mrpc(n: int, seq_len: int, vocab: int, seed: int = 0) -> dict:
    """MRPC-shaped learnable classification (see nlp_example.py)."""
    rng = np.random.default_rng(seed)
    half = seq_len // 2
    ids = rng.integers(10, vocab, size=(n, seq_len), dtype=np.int32)
    token_type = np.concatenate(
        [np.zeros((n, half), np.int32), np.ones((n, seq_len - half), np.int32)], axis=1
    )
    keywords = rng.integers(2, 10, size=n, dtype=np.int32)
    labels = (keywords >= 6).astype(np.int32)
    for pos in (1, 2, 3, 4):
        ids[:, pos] = keywords
    ids[:, 0] = 1
    mask = np.ones((n, seq_len), np.int32)
    return {"input_ids": ids, "token_type_ids": token_type,
            "attention_mask": mask, "labels": labels}


def make_synthetic_images(n: int, size: int = 32, classes: int = 4, seed: int = 0) -> dict:
    """Learnable image classification: class = quadrant holding a bright patch."""
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 0.3, size=(n, size, size, 3)).astype(np.float32)
    labels = rng.integers(0, classes, size=n, dtype=np.int32)
    h = size // 2
    corners = [(0, 0), (0, h), (h, 0), (h, h)]
    for i in range(n):
        r, c = corners[labels[i] % 4]
        x[i, r:r + h, c:c + h, :] += 1.5
    return {"pixel_values": x, "labels": labels}


def add_common_args(parser):
    parser.add_argument("--mixed-precision", default="bf16",
                        choices=["no", "fp16", "bf16", "fp8"])
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--train-size", type=int, default=512)
    parser.add_argument("--eval-size", type=int, default=128)
    return parser


def maybe_force_cpu(args):
    if getattr(args, "cpu", False):
        import jax

        jax.config.update("jax_platforms", "cpu")


def build_tiny_bert_setup(args, accelerator, seq_len: int = 64, optimizer=None):
    """Common scaffold for the by_feature scripts: tiny BERT on synthetic MRPC
    (the reference's by_feature/* scripts all share the BERT-MRPC training body
    and differ in ONE feature each)."""
    import dataclasses

    import jax
    import optax

    from accelerate_tpu import DataLoader
    from accelerate_tpu.models import (
        BertConfig, bert_forward, bert_loss, bert_shard_rules, init_bert,
    )

    config = dataclasses.replace(BertConfig.tiny(), max_seq_len=seq_len, num_labels=2)
    train = make_synthetic_mrpc(args.train_size, seq_len, config.vocab_size, seed=0)
    test = make_synthetic_mrpc(args.eval_size, seq_len, config.vocab_size, seed=1)
    params = init_bert(config, jax.random.PRNGKey(args.seed))
    if optimizer is None:
        optimizer = optax.adam(args.lr)
    train_dl = DataLoader(DictDataset(train), batch_size=args.batch_size,
                          shuffle=True, seed=args.seed)
    eval_dl = DataLoader(DictDataset(test), batch_size=args.batch_size)
    params, optimizer, train_dl, eval_dl = accelerator.prepare(
        params, optimizer, train_dl, eval_dl, shard_rules=bert_shard_rules()
    )
    return {
        "config": config,
        "params": params,
        "optimizer": optimizer,
        "train_dl": train_dl,
        "eval_dl": eval_dl,
        "loss_fn": lambda p, b: bert_loss(p, b, config),
        "logits_fn": lambda p, b: bert_forward(p, b, config),
    }


def evaluate_accuracy(accelerator, eval_step, params, eval_dl) -> float:
    import jax.numpy as jnp
    import numpy as np

    correct = total = 0
    for batch in eval_dl:
        preds = jnp.argmax(eval_step(params, batch), axis=-1)
        g = accelerator.gather_for_metrics({"p": preds, "l": batch["labels"]})
        correct += int(np.sum(np.asarray(g["p"]) == np.asarray(g["l"])))
        total += int(np.asarray(g["l"]).shape[0])
    return correct / max(total, 1)

def make_synthetic_lm(n: int, seq_len: int, vocab: int, seed: int = 0) -> dict:
    """Learnable LM task: each sequence repeats a per-sample period-4 motif, so
    next-token loss falls quickly once the model attends a few tokens back."""
    import numpy as np

    rng = np.random.default_rng(seed)
    motif = rng.integers(2, vocab, size=(n, 4), dtype=np.int32)
    reps = int(np.ceil(seq_len / 4))
    ids = np.tile(motif, (1, reps))[:, :seq_len]
    return {"input_ids": ids}
