"""North-star torch-interop example: the reference's torch training-loop shape,
running on the TPU-native core.

This is a minimally-modified port of the reference's ``examples/nlp_example.py``
torch loop (model/optimizer/scheduler built with torch + transformers;
``accelerator.backward(loss)``; ``optimizer.step()``; ``lr_scheduler.step()``;
eval via ``outputs.logits.argmax(dim=-1)`` + ``gather_for_metrics``). The only
changes are the synthetic offline dataset and dropping the tokenizer. Under the
hood ``prepare`` DLPack-shares the ``nn.Module`` params into a sharded jax
pytree and fx-lowers the model; each training step is ONE fused jitted
forward+backward on the mesh.

Run (CPU 8-dev): XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/torch_interop_nlp_example.py --cpu
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from example_utils import add_common_args, make_synthetic_mrpc, maybe_force_cpu


def training_function(args):
    import torch
    from transformers import BertConfig, BertForSequenceClassification

    from accelerate_tpu import Accelerator, DataLoader

    accelerator = Accelerator(mixed_precision=args.mixed_precision, cpu=args.cpu,
                              rng_seed=args.seed)

    vocab = 200
    torch.manual_seed(args.seed)
    config = BertConfig(
        vocab_size=vocab, hidden_size=64, num_hidden_layers=2, num_attention_heads=2,
        intermediate_size=128, max_position_embeddings=args.seq_len,
        problem_type="single_label_classification", num_labels=2,
        hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
    )
    model = BertForSequenceClassification(config)

    train = make_synthetic_mrpc(args.train_size, args.seq_len, vocab, seed=0)
    test = make_synthetic_mrpc(args.eval_size, args.seq_len, vocab, seed=1)

    class DS:
        def __init__(self, data):
            self.data = data

        def __len__(self):
            return len(self.data["labels"])

        def __getitem__(self, i):
            return {k: v[i].astype(np.int64) if v[i].ndim else np.int64(v[i])
                    for k, v in self.data.items()}

    train_dl = DataLoader(DS(train), batch_size=args.batch_size, shuffle=True, seed=args.seed)
    eval_dl = DataLoader(DS(test), batch_size=args.batch_size)

    optimizer = torch.optim.AdamW(model.parameters(), lr=args.lr)
    lr_scheduler = torch.optim.lr_scheduler.LinearLR(
        optimizer, start_factor=1.0, end_factor=0.1,
        total_iters=args.epochs * max(len(train_dl), 1) * 8,
    )

    # ---- from here down this is the reference's torch loop, verbatim shape ----
    model, optimizer, train_dl, eval_dl, lr_scheduler = accelerator.prepare(
        model, optimizer, train_dl, eval_dl, lr_scheduler
    )

    for epoch in range(args.epochs):
        model.train()
        for batch in train_dl:
            outputs = model(**batch)
            loss = outputs.loss
            accelerator.backward(loss)
            optimizer.step()
            lr_scheduler.step()
            optimizer.zero_grad()

        model.eval()
        correct = total = 0
        for batch in eval_dl:
            with torch.no_grad():
                outputs = model(**batch)
            predictions = outputs.logits.argmax(dim=-1)
            gathered = accelerator.gather_for_metrics(
                {"predictions": predictions, "references": batch["labels"]}
            )
            correct += int(np.sum(np.asarray(gathered["predictions"])
                                  == np.asarray(gathered["references"])))
            total += int(np.asarray(gathered["references"]).shape[0])
        acc = correct / max(total, 1)
        accelerator.print(f"epoch {epoch}: accuracy {acc:.3f} loss {float(loss):.4f}")

    return {"eval_accuracy": acc, "final_loss": float(loss)}


def main():
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--seq-len", type=int, default=32)
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)


if __name__ == "__main__":
    main()
