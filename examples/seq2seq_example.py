"""Seq2seq example: T5-style encoder-decoder fine-tune + greedy eval
(reference acceptance surface includes T5/T0pp through transformers; this is
the native counterpart using ``models/t5.py``).

Task (synthetic, learnable, GENERALIZES held-out): one keyword token is
planted at a random position among distractors; the target spells out a fixed
4-token pattern of the keyword — the decoder must find it via content-based
cross-attention (tiny models reach >0.9 held-out exact match; harder
position-addressed tasks like reversal only memorize at this scale).

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/seq2seq_example.py --cpu
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from example_utils import DictDataset, add_common_args, maybe_force_cpu


def make_keyword_task(n: int, src_len: int, vocab: int, seed: int = 0):
    """src: distractors (40..vocab) with ONE keyword (2..39) planted at a
    random position; tgt: [kw, kw, kw+1, kw] — content lookup + local map."""
    import numpy as np

    rng = np.random.default_rng(seed)
    src = rng.integers(40, vocab, (n, src_len)).astype(np.int32)
    kw = rng.integers(2, 40, n).astype(np.int32)
    pos = rng.integers(0, src_len, n)
    src[np.arange(n), pos] = kw
    tgt = np.stack([kw, kw, (kw + 1) % 40, kw], axis=1).astype(np.int32)
    dec_in = np.concatenate([np.zeros((n, 1), np.int32), tgt[:, :-1]], axis=1)
    return {"input_ids": src, "decoder_input_ids": dec_in, "labels": tgt}


def training_function(args):
    import dataclasses

    import jax
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator, DataLoader
    from accelerate_tpu.models import T5Config, init_t5, t5_greedy_generate, t5_loss, t5_shard_rules

    accelerator = Accelerator(mixed_precision=args.mixed_precision,
                              cpu=args.cpu, rng_seed=args.seed)
    config = dataclasses.replace(T5Config.tiny(), vocab_size=128)
    train = make_keyword_task(args.train_size, args.src_len, config.vocab_size, seed=0)
    test = make_keyword_task(args.eval_size, args.src_len, config.vocab_size, seed=1)
    params = init_t5(config, jax.random.PRNGKey(args.seed))
    train_dl = DataLoader(DictDataset(train), batch_size=args.batch_size,
                          shuffle=True, seed=args.seed)
    params, optimizer, train_dl = accelerator.prepare(
        params, optax.adam(args.lr), train_dl, shard_rules=t5_shard_rules()
    )
    step = accelerator.prepare_train_step(lambda p, b: t5_loss(p, b, config), optimizer)
    opt_state = optimizer.opt_state
    for epoch in range(args.epochs):
        for batch in train_dl:
            params, opt_state, metrics = step(params, opt_state, batch)
        accelerator.print(f"epoch {epoch}: loss {float(metrics['loss']):.4f}")

    # greedy-decode eval: exact-sequence match rate on held-out data
    out = t5_greedy_generate(params, test["input_ids"], config, max_new_tokens=4)
    pred = np.asarray(out)[:, 1:5]  # drop the start token
    exact = float((pred == test["labels"]).all(axis=1).mean())
    accelerator.print(f"exact-match {exact:.3f}")
    return {"train_loss": float(metrics["loss"]), "exact_match": exact}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--src-len", type=int, default=12)
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
