"""CV example: small convnet classification (reference ``examples/cv_example.py``,
ResNet-50 on pet images — same training shape on synthetic data: conv stack via
``lax.conv_general_dilated``, one jitted SPMD step, gather_for_metrics eval).

Run (CPU 8-dev): XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/cv_example.py --cpu
"""

from __future__ import annotations

import argparse
import sys
import os

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from example_utils import DictDataset, add_common_args, make_synthetic_images, maybe_force_cpu


def init_convnet(key, num_classes: int = 4, width: int = 16):
    import jax
    import jax.numpy as jnp

    ks = jax.random.split(key, 4)

    def conv_kernel(k, cin, cout):
        return jax.random.normal(k, (3, 3, cin, cout)) * (1.0 / (3 * (cin ** 0.5)))

    return {
        "conv1": {"kernel": conv_kernel(ks[0], 3, width)},
        "conv2": {"kernel": conv_kernel(ks[1], width, width * 2)},
        "conv3": {"kernel": conv_kernel(ks[2], width * 2, width * 4)},
        "head": {"kernel": jax.random.normal(ks[3], (width * 4, num_classes)) * 0.02,
                 "bias": jnp.zeros((num_classes,))},
    }


def convnet_forward(params, x):
    import jax
    import jax.numpy as jnp

    def block(x, kernel):
        out = jax.lax.conv_general_dilated(
            x, kernel, window_strides=(2, 2), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return jax.nn.relu(out)

    x = block(x, params["conv1"]["kernel"])
    x = block(x, params["conv2"]["kernel"])
    x = block(x, params["conv3"]["kernel"])
    x = jnp.mean(x, axis=(1, 2))  # global average pool
    return x @ params["head"]["kernel"] + params["head"]["bias"]


def training_function(args):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator, DataLoader

    accelerator = Accelerator(mixed_precision=args.mixed_precision, cpu=args.cpu,
                              rng_seed=args.seed)
    train = make_synthetic_images(args.train_size, seed=0)
    test = make_synthetic_images(args.eval_size, seed=1)
    params = init_convnet(jax.random.PRNGKey(args.seed))
    optimizer = optax.adam(args.lr)
    train_dl = DataLoader(DictDataset(train), batch_size=args.batch_size,
                          shuffle=True, seed=args.seed)
    eval_dl = DataLoader(DictDataset(test), batch_size=args.batch_size)
    params, optimizer, train_dl, eval_dl = accelerator.prepare(
        params, optimizer, train_dl, eval_dl
    )

    def loss_fn(p, batch):
        logits = convnet_forward(p, batch["pixel_values"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["labels"]
        ).mean()

    step = accelerator.prepare_train_step(loss_fn, optimizer)
    eval_step = accelerator.prepare_eval_step(
        lambda p, b: convnet_forward(p, b["pixel_values"])
    )

    opt_state = optimizer.opt_state
    for epoch in range(args.epochs):
        for batch in train_dl:
            params, opt_state, metrics = step(params, opt_state, batch)
        correct = total = 0
        for batch in eval_dl:
            preds = jnp.argmax(eval_step(params, batch), axis=-1)
            g = accelerator.gather_for_metrics({"p": preds, "l": batch["labels"]})
            correct += int(np.sum(np.asarray(g["p"]) == np.asarray(g["l"])))
            total += int(np.asarray(g["l"]).shape[0])
        acc = correct / max(total, 1)
        accelerator.print(f"epoch {epoch}: accuracy {acc:.3f} "
                          f"(loss {float(metrics['loss']):.4f})")
    return {"eval_accuracy": acc}


def main():
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)


if __name__ == "__main__":
    main()
