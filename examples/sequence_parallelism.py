"""Ulysses sequence parallelism: train a decoder LM with the sequence dimension
sharded over the ``sp`` mesh axis (reference
``examples/alst_ulysses_sequence_parallelism/sp-alst.py`` — DeepSpeed
ALST/UlyssesSP head-sharding all-to-all, ``accelerator.py:2344-2456``).

TPU-native shape: the prepared DataLoader shards each global batch's sequence
dim over ``sp``; the model's ``attention_fn`` hook swaps in the Ulysses
all-to-all attention (seq-shard ↔ head-shard around the attention core via
``lax.all_to_all`` on the ICI) — no module monkeypatching, no dataloader
adapter class.

Run (sp=4 × dp=2): XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/sequence_parallelism.py --cpu --sp 4 --dp-shard 2
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from example_utils import DictDataset, add_common_args, maybe_force_cpu, make_synthetic_lm


def training_function(args):
    import dataclasses

    import jax
    import optax

    from accelerate_tpu import Accelerator, DataLoader, ParallelismConfig
    from accelerate_tpu.models import LlamaConfig, init_llama, llama_loss, llama_shard_rules
    from accelerate_tpu.parallel.long_context import sequence_parallel_attention

    pc = ParallelismConfig(sp_size=args.sp, dp_shard_size=args.dp_shard)
    accelerator = Accelerator(mixed_precision=args.mixed_precision,
                              parallelism_config=pc, cpu=args.cpu, rng_seed=args.seed)
    accelerator.print(f"mesh: {accelerator.mesh}")

    config = dataclasses.replace(
        LlamaConfig.tiny(), max_seq_len=args.seq_len,
        # Ulysses shards HEADS across sp inside attention: sp must divide n_kv_heads
        n_heads=max(4, args.sp), n_kv_heads=max(4, args.sp),
    )
    train = make_synthetic_lm(args.train_size, args.seq_len, config.vocab_size, seed=0)
    params = init_llama(config, jax.random.PRNGKey(args.seed))
    train_dl = DataLoader(DictDataset(train), batch_size=args.batch_size,
                          shuffle=True, seed=args.seed)
    params, optimizer, train_dl = accelerator.prepare(
        params, optax.adam(args.lr), train_dl, shard_rules=llama_shard_rules()
    )
    attn = sequence_parallel_attention(accelerator.mesh)

    def loss_fn(p, batch):
        return llama_loss(p, batch, config, attention_fn=attn, mesh=accelerator.mesh)

    step = accelerator.prepare_train_step(loss_fn, optimizer)
    opt_state = optimizer.opt_state
    first = last = None
    for epoch in range(args.epochs):
        for batch in train_dl:
            params, opt_state, metrics = step(params, opt_state, batch)
            if first is None:
                first = float(metrics["loss"])
        last = float(metrics["loss"])
        accelerator.print(f"epoch {epoch}: loss {last:.4f}")
    return {"first_loss": first, "train_loss": last}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--sp", type=int, default=4)
    parser.add_argument("--dp-shard", type=int, default=2)
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
