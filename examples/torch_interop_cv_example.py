"""Torch-interop CV example: the reference's ``examples/cv_example.py``
(ResNet-50 image classification) training shape, running a torch CNN through
the TPU-native core.

Like the reference script, the model/optimizer/scheduler are plain torch; the
loop is ``accelerator.backward(loss)`` / ``optimizer.step()``. The CNN crosses
the torch.export ATen bridge — convolution, batch-norm (train-mode batch
statistics, with running-stat updates threaded back through the bridge's
BUFFER_MUTATION channel), max/adaptive pooling — and each training step is one
fused jitted forward+backward. torchvision is absent in this image, so the
model is a hand-written ResNet block stack and the data is a synthetic
"planted-pattern" image task that a CNN must actually learn.

Run (CPU): python examples/torch_interop_cv_example.py --cpu
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from example_utils import add_common_args, maybe_force_cpu


def make_synthetic_images(n: int, side: int, num_classes: int, seed: int = 0):
    """Images whose class is a planted low-frequency pattern (learnable by
    conv features, unlike pure noise)."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, n)
    xs = rng.normal(scale=0.5, size=(n, 3, side, side)).astype(np.float32)
    yy, xx = np.mgrid[0:side, 0:side] / side
    for i, c in enumerate(labels):
        angle = 2 * np.pi * c / num_classes
        pattern = np.sin(4 * (np.cos(angle) * xx + np.sin(angle) * yy) * np.pi)
        xs[i] += pattern.astype(np.float32)
    return {"pixel_values": xs, "labels": labels.astype(np.int64)}


def build_model(num_classes: int, seed: int):
    import torch
    import torch.nn as nn

    class MiniResNet(nn.Module):
        def __init__(self):
            super().__init__()
            self.stem = nn.Conv2d(3, 16, 7, stride=2, padding=3, bias=False)
            self.bn0 = nn.BatchNorm2d(16)
            self.pool = nn.MaxPool2d(3, stride=2, padding=1)
            self.conv1 = nn.Conv2d(16, 32, 3, stride=2, padding=1, bias=False)
            self.bn1 = nn.BatchNorm2d(32)
            self.conv2 = nn.Conv2d(32, 32, 3, padding=1, bias=False)
            self.bn2 = nn.BatchNorm2d(32)
            self.down = nn.Conv2d(16, 32, 1, stride=2, bias=False)
            self.bnd = nn.BatchNorm2d(32)
            self.fc = nn.Linear(32, num_classes)

        def forward(self, pixel_values, labels=None):
            x = self.pool(torch.relu(self.bn0(self.stem(pixel_values))))
            idn = self.bnd(self.down(x))
            x = torch.relu(self.bn1(self.conv1(x)))
            x = self.bn2(self.conv2(x))
            x = torch.relu(x + idn)
            x = nn.functional.adaptive_avg_pool2d(x, (1, 1)).flatten(1)
            logits = self.fc(x)
            out = {"logits": logits}
            if labels is not None:
                out["loss"] = nn.functional.cross_entropy(logits, labels)
            return out

    torch.manual_seed(seed)
    return MiniResNet()


def training_function(args):
    import torch

    from accelerate_tpu import Accelerator, DataLoader

    accelerator = Accelerator(cpu=args.cpu, rng_seed=args.seed)

    num_classes = 4
    model = build_model(num_classes, args.seed)
    train = make_synthetic_images(args.train_size, args.side, num_classes, seed=0)
    test = make_synthetic_images(args.eval_size, args.side, num_classes, seed=1)

    class DS:
        def __init__(self, data):
            self.data = data

        def __len__(self):
            return len(self.data["labels"])

        def __getitem__(self, i):
            return {k: v[i] for k, v in self.data.items()}

    train_dl = DataLoader(DS(train), batch_size=args.batch_size, shuffle=True, seed=args.seed)
    eval_dl = DataLoader(DS(test), batch_size=args.batch_size)

    optimizer = torch.optim.SGD(model.parameters(), lr=args.lr, momentum=0.9)

    # ---- the reference cv_example's torch loop, verbatim shape ---------------
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        model, optimizer, train_dl, eval_dl
    )

    acc = 0.0
    for epoch in range(args.epochs):
        model.train()
        for batch in train_dl:
            outputs = model(**batch)
            loss = outputs["loss"]
            accelerator.backward(loss)
            optimizer.step()
            optimizer.zero_grad()

        model.eval()
        correct = total = 0
        for batch in eval_dl:
            with torch.no_grad():
                outputs = model(pixel_values=batch["pixel_values"])
            predictions = np.asarray(outputs["logits"]).argmax(axis=-1)
            gathered = accelerator.gather_for_metrics(
                {"predictions": predictions, "references": batch["labels"]}
            )
            correct += int(np.sum(np.asarray(gathered["predictions"])
                                  == np.asarray(gathered["references"])))
            total += int(np.asarray(gathered["references"]).shape[0])
        acc = correct / max(total, 1)
        accelerator.print(f"epoch {epoch}: accuracy {acc:.3f} loss {float(loss):.4f}")

    return {"eval_accuracy": acc, "final_loss": float(loss)}


def main():
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--side", type=int, default=32)
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)


if __name__ == "__main__":
    main()
