"""Distributed batch inference with ``split_between_processes`` (reference
``examples/inference/distributed/``): each process takes its slice of the
prompt list, runs the model, results are gathered with object transport.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/inference/distributed_inference.py --cpu
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import add_common_args, maybe_force_cpu


def main_function(args):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import LlamaConfig, init_llama, llama_forward

    accelerator = Accelerator(cpu=args.cpu, rng_seed=args.seed)
    config = LlamaConfig.tiny()
    params = init_llama(config, jax.random.PRNGKey(args.seed))
    fwd = jax.jit(lambda p, ids: llama_forward(p, ids, config, attention_impl="xla"))

    # 37 "prompts" (uneven across processes — padding handled by the split)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(2, config.vocab_size, size=16).astype(np.int32)
               for _ in range(37)]

    results = []
    with accelerator.split_between_processes(prompts, apply_padding=True) as mine:
        for ids in mine:
            logits = fwd(params, ids[None, :])
            next_tok = int(jnp.argmax(logits[0, -1]))
            results.append(next_tok)
    gathered = accelerator.gather_for_metrics(results, use_gather_object=True)
    flat = list(np.asarray(gathered).reshape(-1))[: len(prompts)]
    accelerator.print(f"{len(flat)} prompts → first next-tokens {flat[:8]}")
    assert len(flat) == len(prompts)
    return {"num_results": len(flat)}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    args = parser.parse_args()
    maybe_force_cpu(args)
    main_function(args)
