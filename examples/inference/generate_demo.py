"""Big-model generate demo: KV-cache greedy decoding with load-time and
tokens/sec reporting — the runnable counterpart of the reference's
big-model-inference benchmark table
(``/root/reference/benchmarks/big_model_inference/README.md:27-37``: model
load seconds + s/token under device_map dispatch).

Three modes:
- ``--mode resident``  — params live in HBM, fully jitted cached decode
- ``--mode cpu``       — params CPU-offloaded, paged per layer with prefetch
  (reference ``cpu_offload``)
- ``--mode disk``      — params spilled to an offload folder (reference
  ``disk_offload``)

Resident mode takes ``--tp N --dp N`` to decode over an N×N device mesh
(params TP-sharded by ``llama_shard_rules``, KV cache head-sharded over
``tp`` / batch-sharded over ``dp`` — the multi-chip leg of BASELINE config
#5). Try it without hardware via a virtual mesh:
``XLA_FLAGS=--xla_force_host_platform_device_count=8
python examples/inference/generate_demo.py --cpu --tp 2 --dp 2 --batch 4``

No hub access in this environment, so weights are synthetic at a
configurable size; the mechanics (streamed load → dispatch → cached decode)
are exactly the production path.

Run: python examples/inference/generate_demo.py --model-size tiny --mode cpu
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import maybe_force_cpu


SIZES = {
    # dim, layers, heads, kv_heads — "small" ≈ 110M, "1b" ≈ 1B params
    "tiny": dict(dim=128, n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=512),
    "small": dict(dim=768, n_layers=12, n_heads=12, n_kv_heads=12, vocab_size=32000),
    "1b": dict(dim=2048, n_layers=16, n_heads=32, n_kv_heads=8, vocab_size=32000),
}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model-size", choices=sorted(SIZES), default="tiny")
    parser.add_argument("--mode", choices=["resident", "cpu", "disk"], default="resident")
    parser.add_argument("--batch", type=int, default=1)
    parser.add_argument("--prompt-len", type=int, default=32)
    parser.add_argument("--max-new-tokens", type=int, default=32)
    parser.add_argument("--temperature", type=float, default=0.0,
                        help="> 0 switches to sampled decoding (resident mode)")
    parser.add_argument("--top-k", type=int, default=0)
    parser.add_argument("--top-p", type=float, default=1.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--tp", type=int, default=1,
                        help="tensor-parallel mesh size (resident mode)")
    parser.add_argument("--dp", type=int, default=1,
                        help="data-parallel mesh size (resident mode)")
    parser.add_argument("--cpu", action="store_true", help="force the CPU backend")
    args = parser.parse_args()
    maybe_force_cpu(args)

    import jax
    import jax.numpy as jnp
    import numpy as np

    if args.tp * args.dp > 1:
        if args.mode != "resident":
            parser.error("--tp/--dp mesh decode needs --mode resident")
        if len(jax.devices()) < args.tp * args.dp:
            parser.error(
                f"mesh needs {args.tp * args.dp} devices, have {len(jax.devices())}"
            )

    from accelerate_tpu.big_modeling import cpu_offload, disk_offload
    from accelerate_tpu.generation import (
        generate_dispatched,
        greedy_generate,
        sample_generate,
        unstack_layer_params,
    )
    from accelerate_tpu.models import LlamaConfig, init_llama

    config = LlamaConfig(max_seq_len=args.prompt_len + args.max_new_tokens + 16,
                         **SIZES[args.model_size])

    t0 = time.time()
    params = init_llama(config, jax.random.PRNGKey(0))
    params = jax.tree_util.tree_map(lambda x: x.astype(jnp.bfloat16), params)
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))

    mesh = None
    if args.tp * args.dp > 1:
        from accelerate_tpu.models.transformer import llama_shard_rules
        from accelerate_tpu.parallel.sharding import shard_params
        from accelerate_tpu.parallelism_config import ParallelismConfig

        # canonical ICI-aware mesh (tp innermost -> adjacent chips; warns and
        # falls back to device-order reshape on CPU/virtual meshes)
        mesh = ParallelismConfig(
            dp_replicate_size=args.dp, tp_size=args.tp
        ).build_mesh(jax.devices())
        params, _ = shard_params(params, mesh, rules=llama_shard_rules())

    tmpdir = None
    if args.mode == "resident":
        model = params
    elif args.mode == "cpu":
        model = cpu_offload(unstack_layer_params(params, config))
    else:
        tmpdir = tempfile.mkdtemp(prefix="generate_demo_offload_")
        model = disk_offload(unstack_layer_params(params, config), tmpdir)
    load_s = time.time() - t0

    prompt = np.random.default_rng(0).integers(
        0, config.vocab_size, (args.batch, args.prompt_len)
    ).astype(np.int32)

    if args.mode != "resident" and (
        args.temperature > 0 or args.top_k or args.top_p < 1.0
    ):
        parser.error("sampling flags (--temperature/--top-k/--top-p) need --mode resident; "
                     "dispatched decoding is greedy-only")
    if args.mode == "resident" and args.temperature <= 0 and (args.top_k or args.top_p < 1.0):
        parser.error("--top-k/--top-p need --temperature > 0 (temperature 0 is greedy)")
    if args.mode == "resident":
        if args.temperature > 0:
            out, stats = sample_generate(
                params, prompt, config, max_new_tokens=args.max_new_tokens,
                temperature=args.temperature, top_k=args.top_k, top_p=args.top_p,
                rng_key=jax.random.PRNGKey(args.seed), return_stats=True, mesh=mesh,
            )
        else:
            out, stats = greedy_generate(
                params, prompt, config, max_new_tokens=args.max_new_tokens,
                return_stats=True, mesh=mesh,
            )
    else:
        out, stats = generate_dispatched(
            model, prompt, config, max_new_tokens=args.max_new_tokens, return_stats=True
        )

    print(json.dumps({
        "mode": args.mode if mesh is None else f"resident-mesh(dp={args.dp},tp={args.tp})",
        "model_size": args.model_size,
        "n_params": n_params,
        "load_seconds": round(load_s, 3),
        "prefill_seconds": round(stats["prefill_seconds"], 3),
        "seconds_per_token": round(stats["seconds_per_token"], 4),
        "decode_tokens_per_sec": round(stats["decode_tokens_per_sec"], 2),
        "generated_shape": list(out.shape),
    }))


if __name__ == "__main__":
    main()
