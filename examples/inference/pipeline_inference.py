"""Pipeline-parallel inference (reference ``examples/inference/pippy/``:
``prepare_pippy`` + ScheduleGPipe). Here the model's layer stack is split into
pp stages over the mesh's ``pp`` axis and microbatches flow through a GPipe
schedule built on ``shard_map`` + ``ppermute``.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/inference/pipeline_inference.py --cpu --pp 4
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import add_common_args, maybe_force_cpu


def main_function(args):
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.parallel.pipeline import make_pipeline_forward, split_into_stages

    n_dev = 8
    accelerator = Accelerator(
        parallelism_config=ParallelismConfig(pp_size=args.pp,
                                             dp_shard_size=n_dev // args.pp),
        cpu=args.cpu, rng_seed=args.seed,
    )
    d, n_layers = 64, 8
    keys = jax.random.split(jax.random.PRNGKey(args.seed), n_layers)
    layers = [{"w": jax.random.normal(k, (d, d)) / np.sqrt(d), "b": jnp.zeros((d,))}
              for k in keys]
    stacked = split_into_stages(layers, args.pp)

    def stage_fn(stage_params, x):
        def layer(x, p):
            return jnp.tanh(x @ p["w"] + p["b"]), None

        out, _ = jax.lax.scan(layer, x, stage_params)
        return out

    fwd = jax.jit(make_pipeline_forward(stage_fn, accelerator.mesh,
                                        num_microbatches=args.microbatches))
    x = jax.random.normal(jax.random.PRNGKey(1), (args.batch_size, d))
    out = fwd(stacked, x)
    # parity vs sequential
    ref = x
    for p in layers:
        ref = jnp.tanh(ref @ p["w"] + p["b"])
    err = float(jnp.max(jnp.abs(out - ref)))
    t0 = time.perf_counter()
    out = fwd(stacked, x)
    float(np.asarray(out[0, 0]))
    dt = time.perf_counter() - t0
    accelerator.print(f"pp={args.pp} microbatches={args.microbatches}: "
                      f"max err vs sequential {err:.2e}, step {dt * 1000:.1f} ms")
    assert err < 1e-4
    return {"max_err": err}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--pp", type=int, default=4)
    parser.add_argument("--microbatches", type=int, default=4)
    args = parser.parse_args()
    maybe_force_cpu(args)
    main_function(args)
