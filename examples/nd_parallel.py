"""ND parallelism: DP-replicate × FSDP × TP (× CP) on one mesh (reference
``examples/torch_native_parallelism/nd_parallel.py``: ParallelismConfig builds
the device mesh; here the same axes drive PartitionSpecs and XLA's collectives).

Run (2x2x2): XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/nd_parallel.py --cpu --dp-replicate 2 --fsdp 2 --tp 2
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from example_utils import DictDataset, add_common_args, make_synthetic_mrpc, maybe_force_cpu


def training_function(args):
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator, DataLoader, ParallelismConfig
    from accelerate_tpu.models import (
        BertConfig, bert_forward, bert_loss, bert_shard_rules, init_bert,
    )

    pc = ParallelismConfig(
        dp_replicate_size=args.dp_replicate,
        dp_shard_size=args.fsdp,
        tp_size=args.tp,
        cp_size=args.cp,
    )
    accelerator = Accelerator(mixed_precision=args.mixed_precision,
                              parallelism_config=pc, cpu=args.cpu, rng_seed=args.seed)
    accelerator.print(f"mesh: {accelerator.mesh}")
    accelerator.print(
        f"ranks: dp_replicate={accelerator.parallelism_config.dp_replicate_size} "
        f"dp_shard={accelerator.parallelism_config.dp_shard_size} "
        f"tp={accelerator.parallelism_config.tp_size} cp={accelerator.parallelism_config.cp_size}"
    )

    config = dataclasses.replace(BertConfig.tiny(), max_seq_len=args.seq_len, num_labels=2)
    train = make_synthetic_mrpc(args.train_size, args.seq_len, config.vocab_size, seed=0)
    params = init_bert(config, jax.random.PRNGKey(args.seed))
    optimizer = optax.adam(args.lr)
    train_dl = DataLoader(DictDataset(train), batch_size=args.batch_size,
                          shuffle=True, seed=args.seed)
    # bert_shard_rules: embeddings/attention/mlp sharded over tp, everything
    # (additionally) over dp_shard — the ND composition is just the spec table
    params, optimizer, train_dl = accelerator.prepare(
        params, optimizer, train_dl, shard_rules=bert_shard_rules()
    )
    step = accelerator.prepare_train_step(lambda p, b: bert_loss(p, b, config), optimizer)
    opt_state = optimizer.opt_state
    for epoch in range(args.epochs):
        for batch in train_dl:
            params, opt_state, metrics = step(params, opt_state, batch)
        accelerator.print(f"epoch {epoch}: loss {float(metrics['loss']):.4f}")
    return {"train_loss": float(metrics["loss"])}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--seq-len", type=int, default=64)
    parser.add_argument("--dp-replicate", type=int, default=1)
    parser.add_argument("--fsdp", type=int, default=2)
    parser.add_argument("--tp", type=int, default=2)
    parser.add_argument("--cp", type=int, default=1)
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
