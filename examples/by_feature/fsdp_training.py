"""Feature: FSDP with peak-memory tracking (reference
``examples/by_feature/fsdp_with_peak_mem_tracking.py``). Under GSPMD "FSDP" is
a sharding assignment: params + optimizer state get
``PartitionSpec(('dp_shard',), ...)`` and XLA inserts the all-gather /
reduce-scatter pattern; no wrapper class, no flat-param bookkeeping.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/by_feature/fsdp_training.py --cpu
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import add_common_args, build_tiny_bert_setup, evaluate_accuracy, maybe_force_cpu


def training_function(args):
    import jax

    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.test_utils.testing import memory_allocated_mb

    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        parallelism_config=ParallelismConfig(dp_shard_size=args.fsdp or -1),
        cpu=args.cpu, rng_seed=args.seed,
    )
    setup = build_tiny_bert_setup(args, accelerator)
    # every param leaf is sharded over dp_shard — check one
    spec = accelerator.param_specs
    leaf_specs = {str(s) for s in jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(lambda x: str(x), spec))}
    accelerator.print(f"param shardings in use: {sorted(leaf_specs)[:4]} ...")
    step = accelerator.prepare_train_step(setup["loss_fn"], setup["optimizer"])
    eval_step = accelerator.prepare_eval_step(setup["logits_fn"])
    params, opt_state = setup["params"], setup["optimizer"].opt_state
    for epoch in range(args.epochs):
        for batch in setup["train_dl"]:
            params, opt_state, metrics = step(params, opt_state, batch)
        accelerator.print(
            f"epoch {epoch}: loss {float(metrics['loss']):.4f}, "
            f"live device memory ≈ {memory_allocated_mb():.1f} MB"
        )
    acc = evaluate_accuracy(accelerator, eval_step, params, setup["eval_dl"])
    accelerator.print(f"accuracy {acc:.3f}")
    return {"eval_accuracy": acc}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--fsdp", type=int, default=0, help="dp_shard size (0 = all devices)")
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
