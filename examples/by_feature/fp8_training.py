"""Feature: fp8 training (reference ``examples/torch_native_parallelism/
fsdp2_fp8.py`` + the fp8 benchmark scripts): e4m3/e5m2 matmuls with TE-style
delayed scaling, amax histories threaded through the optimizer partition.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/by_feature/fp8_training.py --cpu
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import add_common_args, maybe_force_cpu


def training_function(args):
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.ops.fp8 import META_KEY, fp8_dense_apply, fp8_dense_init

    accelerator = Accelerator(mixed_precision="fp8", cpu=args.cpu, rng_seed=args.seed)
    k = jax.random.split(jax.random.PRNGKey(args.seed), 3)
    params = {
        "l1": fp8_dense_init(k[0], 64, 256),
        "l2": fp8_dense_init(k[1], 256, 64),
        "head": fp8_dense_init(k[2], 64, 1),
    }
    optimizer = optax.adam(args.lr)
    params, optimizer = accelerator.prepare(params, optimizer)

    W = jax.random.normal(jax.random.PRNGKey(7), (64, 1))
    X = jax.random.normal(jax.random.PRNGKey(8), (args.train_size, 64))
    Y = X @ W

    def loss_fn(p, batch):
        h = jax.nn.gelu(fp8_dense_apply(p["l1"], batch["x"]))
        h = jax.nn.gelu(fp8_dense_apply(p["l2"], h))
        return jnp.mean((fp8_dense_apply(p["head"], h) - batch["y"]) ** 2)

    step = accelerator.prepare_train_step(loss_fn, optimizer)
    opt_state = optimizer.opt_state
    first = None
    for i in range(args.steps):
        params, opt_state, metrics = step(params, opt_state, {"x": X, "y": Y})
        if first is None:
            first = float(metrics["loss"])
    final = float(metrics["loss"])
    hist = params["l1"][META_KEY]["x_hist"]
    accelerator.print(f"fp8 loss {first:.4f} -> {final:.4f}; "
                      f"amax history head {float(hist[0]):.3f}")
    return {"first_loss": first, "final_loss": final}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--steps", type=int, default=100)
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
