"""Feature: early stopping across processes (reference
``examples/by_feature/early_stopping.py``): any process may trip the trigger
(``set_trigger``); ``check_trigger`` all-reduces the flag so every process
stops on the same step — no desync hangs.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/by_feature/early_stopping.py --cpu
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import add_common_args, build_tiny_bert_setup, evaluate_accuracy, maybe_force_cpu


class EarlyStopper:
    def __init__(self, patience: int = 2, min_delta: float = 1e-4):
        self.patience, self.min_delta = patience, min_delta
        self.best, self.bad = float("inf"), 0

    def should_stop(self, loss: float) -> bool:
        if loss < self.best - self.min_delta:
            self.best, self.bad = loss, 0
            return False
        self.bad += 1
        return self.bad >= self.patience


def training_function(args):
    from accelerate_tpu import Accelerator

    accelerator = Accelerator(mixed_precision=args.mixed_precision, cpu=args.cpu,
                              rng_seed=args.seed)
    setup = build_tiny_bert_setup(args, accelerator)
    step = accelerator.prepare_train_step(setup["loss_fn"], setup["optimizer"])
    eval_step = accelerator.prepare_eval_step(setup["logits_fn"])
    params, opt_state = setup["params"], setup["optimizer"].opt_state
    stopper = EarlyStopper(patience=args.patience)
    stopped = False
    for epoch in range(args.epochs):
        for batch in setup["train_dl"]:
            params, opt_state, metrics = step(params, opt_state, batch)
            if stopper.should_stop(float(metrics["loss"])):
                accelerator.set_trigger()
            # collective: either every process breaks here or none does
            if accelerator.check_trigger():
                accelerator.print(f"early stop inside epoch {epoch}")
                stopped = True
                break
        if stopped:
            break
    acc = evaluate_accuracy(accelerator, eval_step, params, setup["eval_dl"])
    accelerator.print(f"final accuracy {acc:.3f} (stopped={stopped})")
    return {"eval_accuracy": acc, "stopped": stopped}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--patience", type=int, default=3)
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
