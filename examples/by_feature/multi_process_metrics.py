"""Feature: correct distributed eval metrics (reference
``examples/by_feature/multi_process_metrics.py``): ``gather_for_metrics``
concatenates per-process shards AND drops the duplicated samples the
even-batches wraparound added in the final batch, so metric counts match the
true dataset size.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/by_feature/multi_process_metrics.py --cpu
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import add_common_args, build_tiny_bert_setup, maybe_force_cpu


def training_function(args):
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu import Accelerator

    # eval size deliberately NOT divisible by batch*devices → wraparound occurs
    args.eval_size = args.eval_size + 7
    accelerator = Accelerator(mixed_precision=args.mixed_precision, cpu=args.cpu,
                              rng_seed=args.seed)
    setup = build_tiny_bert_setup(args, accelerator)
    step = accelerator.prepare_train_step(setup["loss_fn"], setup["optimizer"])
    eval_step = accelerator.prepare_eval_step(setup["logits_fn"])
    params, opt_state = setup["params"], setup["optimizer"].opt_state
    for batch in setup["train_dl"]:
        params, opt_state, _ = step(params, opt_state, batch)

    all_preds, all_labels = [], []
    for batch in setup["eval_dl"]:
        preds = jnp.argmax(eval_step(params, batch), axis=-1)
        g = accelerator.gather_for_metrics({"p": preds, "l": batch["labels"]})
        all_preds.append(np.asarray(g["p"]))
        all_labels.append(np.asarray(g["l"]))
    preds, labels = np.concatenate(all_preds), np.concatenate(all_labels)
    # the trimmed count equals the true dataset size — no duplicate samples
    assert preds.shape[0] == args.eval_size, (preds.shape, args.eval_size)
    acc = float(np.mean(preds == labels))
    accelerator.print(f"eval on exactly {preds.shape[0]} samples: accuracy {acc:.3f}")
    return {"eval_accuracy": acc, "eval_count": int(preds.shape[0])}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
