"""DeepSpeed-config-file training (reference
``examples/by_feature/deepspeed_with_config_support.py``): the ds_config.json
is the source of truth — ZeRO stage, precision, accumulation, clipping, and
the optimizer/scheduler hyperparameters all come from the file; the script
passes :class:`DummyOptim`/:class:`DummyScheduler` placeholders exactly like a
reference script ported from DeepSpeed.

On TPU the stages become shardings (stage 1 = optimizer-state sharding over
replicas; stages 2-3 = FSDP NamedSharding; cpu offload = host-resident
optimizer state via XLA memory kinds) — same file, TPU-native execution.

Run (CPU 8-dev): python examples/by_feature/deepspeed_with_config_support.py --cpu
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import add_common_args, build_tiny_bert_setup, maybe_force_cpu


def training_function(args):
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import DeepSpeedPlugin, DummyOptim, DummyScheduler

    with open(args.ds_config) as f:
        ds_config = json.load(f)

    plugin = DeepSpeedPlugin(hf_ds_config=ds_config)
    accelerator = Accelerator(deepspeed_plugin=plugin, cpu=args.cpu, rng_seed=args.seed)
    accelerator.print(
        f"zero_stage={plugin.zero_stage} precision={accelerator.mixed_precision} "
        f"accum={plugin.gradient_accumulation_steps}"
    )

    setup = build_tiny_bert_setup(args, accelerator)
    # placeholders: real hyperparameters come from the ds config; "auto"
    # values fall back to these
    optimizer = DummyOptim(lr=args.lr)
    # the schedule counts OPTIMIZER steps: micro-batches / accumulation (the
    # ACCELERATOR's resolved value — env protocol may set it, not just the
    # ds config)
    accum = accelerator.gradient_accumulation_steps
    micro_steps = args.epochs * max(len(setup["train_dl"]), 1)
    scheduler = DummyScheduler(
        optimizer,
        total_num_steps=max(micro_steps // accum, 1),
        warmup_num_steps=2,
    )
    params, optimizer, scheduler = accelerator.prepare(
        setup["params"], optimizer, scheduler
    )
    step = accelerator.prepare_train_step(setup["loss_fn"], optimizer)
    opt_state = optimizer.opt_state

    first = last = None
    micro = 0
    for epoch in range(args.epochs):
        for batch in setup["train_dl"]:
            params, opt_state, metrics = step(params, opt_state, batch)
            loss = float(np.asarray(metrics["loss"]))
            if first is None:
                first = loss
            last = loss
            micro += 1
            # the schedule counts OPTIMIZER steps; the compiled step applies
            # the inner update only on accumulation boundaries
            if micro % accum == 0:
                scheduler.step()
    accelerator.print(f"loss {first:.4f} -> {last:.4f} (lr now {scheduler.get_last_lr()})")
    assert last < first, "no learning"
    return {"first_loss": first, "final_loss": last}


def main():
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument(
        "--ds-config",
        default=os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "deepspeed_config_templates", "zero_stage1_config.json",
        ),
    )
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)


if __name__ == "__main__":
    main()
