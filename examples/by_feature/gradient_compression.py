"""Gradient compression (the reference's ``by_feature/ddp_comm_hook.py``):
DDP comm hooks (fp16/bf16 compress) shrink the allreduce payload. Under SPMD
there is no hook registry — the same effect is a cast in the gradient path
before XLA's compiler-inserted reduction, expressed as an optax transform.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/by_feature/gradient_compression.py --cpu --compress bf16
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import add_common_args, build_tiny_bert_setup, evaluate_accuracy, maybe_force_cpu


def compress_gradients(dtype_name: str):
    """optax transform casting grads to a compressed wire dtype and back —
    the SPMD analogue of DDPCommunicationHookType.FP16/BF16 (reference
    ``utils/dataclasses.py:134-240``). Placed FIRST in the chain, the cast
    happens before the (compiler-scheduled) cross-replica reduction reads the
    values, so the collective moves half the bytes."""
    import jax
    import jax.numpy as jnp
    import optax

    wire = {"bf16": jnp.bfloat16, "fp16": jnp.float16}[dtype_name]

    def update(updates, state, params=None):
        compressed = jax.tree_util.tree_map(
            lambda g: g.astype(wire).astype(g.dtype) if g.dtype == jnp.float32 else g,
            updates,
        )
        return compressed, state

    return optax.GradientTransformation(lambda p: optax.EmptyState(), update)


def training_function(args):
    import optax

    from accelerate_tpu import Accelerator

    accelerator = Accelerator(mixed_precision=args.mixed_precision,
                              cpu=args.cpu, rng_seed=args.seed)
    chain = [optax.adam(args.lr)]
    if args.compress != "none":
        chain.insert(0, compress_gradients(args.compress))
    setup = build_tiny_bert_setup(args, accelerator, optimizer=optax.chain(*chain))
    step = accelerator.prepare_train_step(setup["loss_fn"], setup["optimizer"])
    eval_step = accelerator.prepare_eval_step(setup["logits_fn"])
    params, opt_state = setup["params"], setup["optimizer"].opt_state
    for epoch in range(args.epochs):
        for batch in setup["train_dl"]:
            params, opt_state, metrics = step(params, opt_state, batch)
    acc = evaluate_accuracy(accelerator, eval_step, params, setup["eval_dl"])
    accelerator.print(f"accuracy {acc:.3f} (compress={args.compress})")
    return {"eval_accuracy": acc}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--compress", choices=["none", "bf16", "fp16"], default="bf16")
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
