"""Gradient compression (the reference's ``by_feature/ddp_comm_hook.py``):
DDP comm hooks (fp16/bf16 compress) shrink the allreduce payload.

Under SPMD the WIRE compression is already owned by the precision policy: with
``mixed_precision="bf16"`` the backward pass computes bf16 gradients, so the
compiler-inserted cross-replica reduction moves bf16 — the fp16/bf16 comm-hook
payload saving is inherent, no hook registry needed. What this example adds on
top is the hook's other half: KEEPING the gradient signal compressed through
the optimizer path, expressed as an optax transform (round-trip cast) placed
ahead of the update — demonstrating where reference comm-hook users hang
custom gradient processing in this framework.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/by_feature/gradient_compression.py --cpu --compress bf16
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import add_common_args, build_tiny_bert_setup, evaluate_accuracy, maybe_force_cpu


def compress_gradients(dtype_name: str):
    """optax transform bounding the gradient signal to a compressed dtype
    (round-trip cast) before the optimizer consumes it — the update-side
    analogue of DDPCommunicationHookType.FP16/BF16 (reference
    ``utils/dataclasses.py:134-240``). NOTE: this runs AFTER the
    compiler-inserted gradient reduction; the reduction itself already moves
    bf16 bytes whenever the bf16 precision policy is active."""
    import jax
    import jax.numpy as jnp
    import optax

    wire = {"bf16": jnp.bfloat16, "fp16": jnp.float16}[dtype_name]

    def update(updates, state, params=None):
        compressed = jax.tree_util.tree_map(
            lambda g: g.astype(wire).astype(g.dtype) if g.dtype == jnp.float32 else g,
            updates,
        )
        return compressed, state

    return optax.GradientTransformation(lambda p: optax.EmptyState(), update)


def training_function(args):
    import optax

    from accelerate_tpu import Accelerator

    accelerator = Accelerator(mixed_precision=args.mixed_precision,
                              cpu=args.cpu, rng_seed=args.seed)
    chain = [optax.adam(args.lr)]
    if args.compress != "none":
        chain.insert(0, compress_gradients(args.compress))
    setup = build_tiny_bert_setup(args, accelerator, optimizer=optax.chain(*chain))
    step = accelerator.prepare_train_step(setup["loss_fn"], setup["optimizer"])
    eval_step = accelerator.prepare_eval_step(setup["logits_fn"])
    params, opt_state = setup["params"], setup["optimizer"].opt_state
    for epoch in range(args.epochs):
        for batch in setup["train_dl"]:
            params, opt_state, metrics = step(params, opt_state, batch)
    acc = evaluate_accuracy(accelerator, eval_step, params, setup["eval_dl"])
    accelerator.print(f"accuracy {acc:.3f} (compress={args.compress})")
    return {"eval_accuracy": acc}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--compress", choices=["none", "bf16", "fp16"], default="bf16")
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
