"""Feature: sequence packing — several documents per fixed-shape row.

Static shapes are the TPU contract; padding every document to max length
multiplies zeros on the MXU. `pack_sequences` lays documents end-to-end with
per-token segment ids; the model isolates attention per document, restarts
rope positions, and the loss skips boundary/padding targets (the reference's
closest pressure point is
``examples/by_feature/gradient_accumulation_for_autoregressive_models.py`` —
token-weighted batching for variable-length causal LMs).

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/by_feature/sequence_packing.py --cpu
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import add_common_args, maybe_force_cpu


def training_function(args):
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import LlamaConfig, init_llama, llama_loss
    from accelerate_tpu.utils import pack_sequences

    accelerator = Accelerator(mixed_precision=args.mixed_precision, cpu=args.cpu, rng_seed=args.seed)
    cfg = LlamaConfig.tiny()
    rng = np.random.default_rng(args.seed)
    # synthetic corpus with high length variance (the case packing wins)
    docs = [
        rng.integers(1, cfg.vocab_size, size=int(rng.integers(6, args.seq_len))).tolist()
        for _ in range(args.num_docs)
    ]
    ids, segs = pack_sequences(docs, seq_len=args.seq_len)
    packed_util = float((segs > 0).mean())
    padded_rows = len(docs)  # one padded row per doc without packing
    accelerator.print(
        f"{len(docs)} docs → {ids.shape[0]} packed rows (vs {padded_rows} padded); "
        f"token utilization {packed_util:.0%}"
    )

    params, opt = accelerator.prepare(init_llama(cfg, jax.random.PRNGKey(args.seed)), optax.adamw(3e-3))
    step = accelerator.prepare_train_step(
        lambda p, b: llama_loss(p, b, cfg, attention_impl="xla"), opt
    )
    opt_state = opt.opt_state
    # pad rows UP to a device-count multiple with all-padding rows (segment id
    # 0 everywhere → zero loss contribution) so no document is dropped
    n_dev = accelerator.partial_state.num_devices
    n = ((ids.shape[0] + n_dev - 1) // n_dev) * n_dev
    if n != ids.shape[0]:
        pad_rows = n - ids.shape[0]
        ids = np.concatenate([ids, np.zeros((pad_rows, args.seq_len), ids.dtype)])
        segs = np.concatenate([segs, np.zeros((pad_rows, args.seq_len), segs.dtype)])
        accelerator.print(f"padded with {pad_rows} empty rows to reach a multiple of {n_dev}")
    batch = {"input_ids": jnp.asarray(ids), "segment_ids": jnp.asarray(segs)}
    final = None
    for epoch in range(args.epochs):
        for _ in range(8):
            params, opt_state, metrics = step(params, opt_state, batch)
        final = float(metrics["loss"])
        accelerator.print(f"epoch {epoch}: loss {final:.4f}")
    return {"train_loss": final, "token_utilization": packed_util}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--seq_len", type=int, default=64)
    parser.add_argument("--num_docs", type=int, default=64)
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
