"""FSDP training with peak-memory tracking (reference
``by_feature/fsdp_with_peak_mem_tracking.py``: a TorchTracemalloc context
around the epoch reporting CUDA peaks). TPU-native shape: per-device live/peak
bytes come from ``device.memory_stats()``, and the COMPILED step's planned
footprint comes from ``compiled.memory_analysis()`` — available before the
first batch runs, something torch cannot offer.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/by_feature/fsdp_with_peak_mem_tracking.py --cpu --fsdp 8
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import add_common_args, build_tiny_bert_setup, evaluate_accuracy, maybe_force_cpu


def device_memory_report():
    """Best-effort {live_bytes, peak_bytes} for device 0 (TPU backends expose
    memory_stats; CPU returns zeros)."""
    import jax

    stats = getattr(jax.devices()[0], "memory_stats", lambda: None)() or {}
    return {
        "live_bytes": int(stats.get("bytes_in_use", 0)),
        "peak_bytes": int(stats.get("peak_bytes_in_use", 0)),
    }


def training_function(args):
    import jax

    from accelerate_tpu import Accelerator, ParallelismConfig

    pc = ParallelismConfig(dp_shard_size=args.fsdp) if args.fsdp else None
    accelerator = Accelerator(mixed_precision=args.mixed_precision,
                              parallelism_config=pc, cpu=args.cpu, rng_seed=args.seed)
    setup = build_tiny_bert_setup(args, accelerator)
    params, optimizer = setup["params"], setup["optimizer"]

    # compiled-step memory plan BEFORE running a batch: lower + compile the
    # train step and ask XLA for its temp/argument/output allocation sizes
    step_unjit = accelerator._build_train_step(setup["loss_fn"], optimizer, False, False)
    batch0 = next(iter(setup["train_dl"]))
    # donate params/opt_state exactly like the prepared step does, or the plan
    # double-counts the parameter memory (old + updated buffers)
    compiled = (
        jax.jit(step_unjit, donate_argnums=(0, 1))
        .lower(params, optimizer.opt_state, batch0)
        .compile()
    )
    mem = compiled.memory_analysis()
    planned = {
        "temp_bytes": int(getattr(mem, "temp_size_in_bytes", 0)),
        "argument_bytes": int(getattr(mem, "argument_size_in_bytes", 0)),
        "output_bytes": int(getattr(mem, "output_size_in_bytes", 0)),
    }
    accelerator.print(f"compiled-step memory plan: {planned}")

    step = accelerator.prepare_train_step(setup["loss_fn"], optimizer)
    eval_step = accelerator.prepare_eval_step(setup["logits_fn"])
    opt_state = optimizer.opt_state
    for epoch in range(args.epochs):
        for batch in setup["train_dl"]:
            params, opt_state, metrics = step(params, opt_state, batch)
        report = device_memory_report()
        accelerator.print(
            f"epoch {epoch}: loss {float(metrics['loss']):.4f} "
            f"live {report['live_bytes'] >> 20} MiB peak {report['peak_bytes'] >> 20} MiB"
        )
    acc = evaluate_accuracy(accelerator, eval_step, params, setup["eval_dl"])
    return {"eval_accuracy": acc, "planned": planned, "device_memory": device_memory_report()}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--fsdp", type=int, default=8)
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
