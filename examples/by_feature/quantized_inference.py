"""Feature: quantized inference (reference ``utils/bnb.py`` usage): load a
checkpoint 4-bit/8-bit quantized — weights live in HBM as codes+scales, the
dequant fuses into each matmul.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/by_feature/quantized_inference.py --cpu --bits 4
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import add_common_args, maybe_force_cpu


def main_function(args):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu import QuantizationConfig, load_and_quantize_model
    from accelerate_tpu.checkpointing import save_model
    from accelerate_tpu.models import LlamaConfig, init_llama, llama_forward
    from accelerate_tpu.ops.quantization import quantized_byte_size
    from accelerate_tpu.utils.modeling import total_byte_size

    config = LlamaConfig.tiny()
    params = init_llama(config, jax.random.PRNGKey(args.seed))
    with tempfile.TemporaryDirectory() as ckpt:
        save_model(params, ckpt)
        template = jax.eval_shape(lambda: params)
        qcfg = QuantizationConfig(load_in_8bit=args.bits == 8,
                                  load_in_4bit=args.bits == 4, min_size=4096)
        qparams, _ = load_and_quantize_model(template, qcfg, checkpoint=ckpt)

    dense_mb = total_byte_size(params) / 1e6
    quant_mb = quantized_byte_size(qparams) / 1e6
    print(f"{args.bits}-bit: {dense_mb:.2f} MB dense -> {quant_mb:.2f} MB "
          f"({dense_mb / quant_mb:.1f}x smaller)")

    ids = np.random.default_rng(0).integers(2, config.vocab_size, (2, 32)).astype(np.int32)
    fwd = jax.jit(lambda p, i: llama_forward(p, i, config, attention_impl="xla"))
    ref = llama_forward(params, ids, config, attention_impl="xla")
    out = fwd(qparams, ids)
    rel = float(jnp.linalg.norm((out - ref).astype(jnp.float32))
                / jnp.linalg.norm(ref.astype(jnp.float32)))
    print(f"logits relative error vs dense: {rel:.4f}")
    return {"compression": dense_mb / quant_mb, "rel_err": rel}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--bits", type=int, default=4, choices=[4, 8])
    args = parser.parse_args()
    maybe_force_cpu(args)
    main_function(args)
