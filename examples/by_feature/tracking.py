"""Feature: experiment tracking (reference ``examples/by_feature/tracking.py``):
``init_trackers`` fans config+metrics out to every enabled tracker (jsonl is
the always-available file backend; tensorboard/wandb/mlflow activate when
installed), all main-process-only.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/by_feature/tracking.py --cpu --project-dir /tmp/track_demo
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import add_common_args, build_tiny_bert_setup, evaluate_accuracy, maybe_force_cpu


def training_function(args):
    from accelerate_tpu import Accelerator

    accelerator = Accelerator(mixed_precision=args.mixed_precision, cpu=args.cpu,
                              log_with="jsonl", project_dir=args.project_dir,
                              rng_seed=args.seed)
    accelerator.init_trackers("tracking_example", config=vars(args))
    setup = build_tiny_bert_setup(args, accelerator)
    step = accelerator.prepare_train_step(setup["loss_fn"], setup["optimizer"])
    eval_step = accelerator.prepare_eval_step(setup["logits_fn"])
    params, opt_state = setup["params"], setup["optimizer"].opt_state
    global_step = 0
    for epoch in range(args.epochs):
        for batch in setup["train_dl"]:
            params, opt_state, metrics = step(params, opt_state, batch)
            global_step += 1
            if global_step % 10 == 0:
                accelerator.log({"train_loss": float(metrics["loss"])}, step=global_step)
        acc = evaluate_accuracy(accelerator, eval_step, params, setup["eval_dl"])
        accelerator.log({"eval_accuracy": acc}, step=global_step)
        accelerator.print(f"epoch {epoch}: accuracy {acc:.3f}")
    accelerator.end_training()
    log_file = os.path.join(args.project_dir, "tracking_example", "metrics.jsonl")
    accelerator.print(f"metrics at {log_file}: {os.path.isfile(log_file)}")
    return {"eval_accuracy": acc}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--project-dir", default="/tmp/accelerate_tpu_track_demo")
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
