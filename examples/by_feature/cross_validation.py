"""Feature: k-fold cross validation (reference
``examples/by_feature/cross_validation.py`` — datasets-powered fold splits,
one full train per fold, fold metrics averaged). The fold loop is plain host
code; everything inside a fold is the standard prepared SPMD training slice.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/by_feature/cross_validation.py --cpu --folds 3
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import (
    DictDataset,
    add_common_args,
    evaluate_accuracy,
    make_synthetic_mrpc,
    maybe_force_cpu,
)


def training_function(args):
    import dataclasses

    import jax
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator, DataLoader
    from accelerate_tpu.models import (
        BertConfig, bert_forward, bert_loss, bert_shard_rules, init_bert,
    )

    accelerator = Accelerator(mixed_precision=args.mixed_precision, cpu=args.cpu,
                              rng_seed=args.seed)
    seq_len = 64
    config = dataclasses.replace(BertConfig.tiny(), max_seq_len=seq_len, num_labels=2)
    data = make_synthetic_mrpc(args.train_size, seq_len, config.vocab_size, seed=0)
    n = len(data["labels"])
    perm = np.random.default_rng(args.seed).permutation(n)
    folds = np.array_split(perm, args.folds)

    accuracies = []
    for fold_idx in range(args.folds):
        eval_idx = folds[fold_idx]
        train_idx = np.concatenate([folds[i] for i in range(args.folds) if i != fold_idx])
        train = {k: v[train_idx] for k, v in data.items()}
        evald = {k: v[eval_idx] for k, v in data.items()}

        params = init_bert(config, jax.random.PRNGKey(args.seed + fold_idx))
        optimizer = optax.adam(args.lr)
        train_dl = DataLoader(DictDataset(train), batch_size=args.batch_size,
                              shuffle=True, seed=args.seed)
        eval_dl = DataLoader(DictDataset(evald), batch_size=args.batch_size)
        params, optimizer, train_dl, eval_dl = accelerator.prepare(
            params, optimizer, train_dl, eval_dl, shard_rules=bert_shard_rules()
        )
        step = accelerator.prepare_train_step(lambda p, b: bert_loss(p, b, config), optimizer)
        eval_step = accelerator.prepare_eval_step(lambda p, b: bert_forward(p, b, config))
        opt_state = optimizer.opt_state
        for epoch in range(args.epochs):
            for batch in train_dl:
                params, opt_state, _ = step(params, opt_state, batch)
        acc = evaluate_accuracy(accelerator, eval_step, params, eval_dl)
        accelerator.print(f"fold {fold_idx}: accuracy {acc:.3f}")
        accuracies.append(acc)
        accelerator.free_memory()

    mean_acc = float(np.mean(accuracies))
    accelerator.print(f"cross-validated accuracy: {mean_acc:.3f} over {args.folds} folds")
    return {"eval_accuracy": mean_acc}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--folds", type=int, default=3)
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
