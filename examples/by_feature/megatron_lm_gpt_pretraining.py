"""Feature: Megatron-LM-style GPT pretraining via the plugin shim (reference
``examples/by_feature/megatron_lm_gpt_pretraining.py`` drives the Megatron
CUDA engine). There is no engine here: ``MegatronLMPlugin(tp_degree=...,
num_micro_batches=...)`` maps straight onto the native mesh — tensor
parallelism becomes GSPMD shardings over the ``tp`` axis, micro-batching
becomes in-graph gradient accumulation — and the training loop is the same
one every other lesson uses.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/by_feature/megatron_lm_gpt_pretraining.py --cpu --tp 2
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import DictDataset, add_common_args, make_synthetic_lm, maybe_force_cpu


def training_function(args):
    import jax
    import optax

    from accelerate_tpu import Accelerator, DataLoader
    from accelerate_tpu.models import LlamaConfig, init_llama, llama_loss, llama_shard_rules
    from accelerate_tpu.utils import MegatronLMPlugin

    plugin = MegatronLMPlugin(
        tp_degree=args.tp,
        num_micro_batches=args.num_micro_batches,
        # engine-tuning knobs are accepted for config compatibility; XLA owns
        # fusion/recompute decisions (recompute_activations maps to remat)
        recompute_activations=False,
    )
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision, megatron_lm_plugin=plugin,
        cpu=args.cpu, rng_seed=args.seed,
    )
    pc = accelerator.parallelism_config
    accelerator.print(
        f"megatron plugin -> mesh: tp={pc.tp_size} pp={pc.pp_size} "
        f"dp_shard={pc.dp_shard_size}, grad accum={accelerator.gradient_accumulation_steps}"
    )

    config = LlamaConfig.tiny()
    data = make_synthetic_lm(args.train_size, args.seq_len, config.vocab_size, seed=args.seed)
    params = init_llama(config, jax.random.PRNGKey(args.seed))
    params, opt, train_dl = accelerator.prepare(
        params,
        optax.adamw(args.lr),
        DataLoader(DictDataset(data), batch_size=args.batch_size),
        shard_rules=llama_shard_rules(),
    )
    # the plugin's tp_degree is live: at least one weight is tp-sharded
    tp_sharded = any(
        "tp" in str(getattr(x, "sharding", None).spec)
        for x in jax.tree_util.tree_leaves(params)
        if getattr(x, "sharding", None) is not None
    )
    if pc.tp_size > 1:
        assert tp_sharded, "tp_degree did not reach the mesh"

    step = accelerator.prepare_train_step(
        lambda p, b: llama_loss(p, b, config, attention_impl="xla",
                                mesh=accelerator.mesh, remat=plugin.remat),
        opt,
    )
    opt_state = opt.opt_state
    for epoch in range(args.epochs):
        for batch in train_dl:
            params, opt_state, metrics = step(params, opt_state, batch)
        accelerator.print(f"epoch {epoch}: loss {float(metrics['loss']):.4f}")
    return {"train_loss": float(metrics["loss"]), "tp_sharded": tp_sharded}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--tp", type=int, default=2, help="tensor-parallel degree")
    parser.add_argument("--num_micro_batches", type=int, default=2)
    parser.add_argument("--seq_len", type=int, default=64)
    args = parser.parse_args()  # --lr/--epochs/... come from add_common_args
    maybe_force_cpu(args)
    training_function(args)
