"""Feature: optimizer-state host offload — the ZeRO-Offload capability
(reference: ``DeepSpeedPlugin(offload_optimizer_device="cpu")`` routing to the
DeepSpeed CPU-Adam engine, ``examples/by_feature/deepspeed_with_config_support.py``).

TPU-native form: the optimizer state rests in host RAM as ``pinned_host``
arrays; the compiled train step stages it into HBM, updates, and commits it
back — all inside one XLA program. On backends without memory-kind compilation
(the CPU mesh this example also runs on) it degrades to a warning and keeps
state in HBM, so the script works everywhere.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/by_feature/zero_offload.py --cpu
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import add_common_args, build_tiny_bert_setup, evaluate_accuracy, maybe_force_cpu


def training_function(args):
    import jax

    from accelerate_tpu import Accelerator, DeepSpeedPlugin
    from accelerate_tpu.parallel import host_offload_supported

    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        deepspeed_plugin=DeepSpeedPlugin(zero_stage=2, offload_optimizer_device="cpu"),
        cpu=args.cpu, rng_seed=args.seed,
    )
    accelerator.print(f"host offload supported on this backend: {host_offload_supported()}")
    setup = build_tiny_bert_setup(args, accelerator)
    step = accelerator.prepare_train_step(setup["loss_fn"], setup["optimizer"])
    eval_step = accelerator.prepare_eval_step(setup["logits_fn"])
    params, opt_state = setup["params"], setup["optimizer"].opt_state
    kinds = {
        getattr(x.sharding, "memory_kind", None)
        for x in jax.tree_util.tree_leaves(opt_state)
        if hasattr(x, "sharding")
    }
    accelerator.print(f"optimizer-state memory kinds: {sorted(k for k in kinds if k)}")
    for epoch in range(args.epochs):
        for batch in setup["train_dl"]:
            params, opt_state, metrics = step(params, opt_state, batch)
        accelerator.print(f"epoch {epoch}: loss {float(metrics['loss']):.4f}")
    acc = evaluate_accuracy(accelerator, eval_step, params, setup["eval_dl"])
    accelerator.print(f"accuracy {acc:.3f}")
    return {"eval_accuracy": acc}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
