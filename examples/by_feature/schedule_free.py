"""Feature: schedule-free optimization (reference
``examples/by_feature/schedule_free.py`` — Meta's schedulefree AdamW, no LR
schedule needed). TPU-native: ``optax.contrib.schedule_free_adamw``, which
keeps the same interpolation-based y/z iterates; evaluation must read the
``schedule_free_eval_params`` projection, not the raw train params.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/by_feature/schedule_free.py --cpu
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import add_common_args, build_tiny_bert_setup, evaluate_accuracy, maybe_force_cpu


def training_function(args):
    import optax

    from accelerate_tpu import Accelerator

    accelerator = Accelerator(mixed_precision=args.mixed_precision, cpu=args.cpu,
                              rng_seed=args.seed)
    optimizer = optax.contrib.schedule_free_adamw(args.lr, warmup_steps=10)
    setup = build_tiny_bert_setup(args, accelerator, optimizer=optimizer)
    step = accelerator.prepare_train_step(setup["loss_fn"], setup["optimizer"])
    eval_step = accelerator.prepare_eval_step(setup["logits_fn"])
    params, opt_state = setup["params"], setup["optimizer"].opt_state
    for epoch in range(args.epochs):
        for batch in setup["train_dl"]:
            params, opt_state, metrics = step(params, opt_state, batch)
    # schedule-free keeps averaged iterates in the optimizer state; evaluation
    # uses their projection rather than the live train params
    eval_params = optax.contrib.schedule_free_eval_params(_inner_state(opt_state), params)
    acc = evaluate_accuracy(accelerator, eval_step, eval_params, setup["eval_dl"])
    accelerator.print(f"accuracy {acc:.3f} (schedule-free, no LR schedule)")
    return {"eval_accuracy": acc}


def _inner_state(opt_state):
    """Unwrap MultiSteps/loss-scale wrappers down to the ScheduleFreeState."""
    import optax

    state = opt_state
    while not isinstance(state, optax.contrib.ScheduleFreeState):
        if hasattr(state, "inner_opt_state"):
            state = state.inner_opt_state
        elif isinstance(state, (tuple, list)) and state:
            state = state[0]
        else:
            raise ValueError("no ScheduleFreeState found in optimizer state")
    return state


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
