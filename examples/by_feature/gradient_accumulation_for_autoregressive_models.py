"""Gradient accumulation for autoregressive models: token-weighted accumulation
(reference ``examples/by_feature/gradient_accumulation_for_autoregressive_models.py``).

The subtlety the reference script teaches: with variable numbers of REAL
(non-padded) tokens per micro-batch, averaging micro-batch mean-losses weights
short batches the same as long ones. The fix is to weight each micro-batch's
contribution by its real-token count — here the loss is summed over valid
tokens and divided by the PER-ACCUMULATION-WINDOW token count, so the compiled
accumulation (optax.MultiSteps mean of micro-grads) reproduces the exact
global-batch gradient.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/by_feature/gradient_accumulation_for_autoregressive_models.py --cpu
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import DictDataset, add_common_args, maybe_force_cpu


def make_varlen_lm(n: int, seq_len: int, vocab: int, seed: int = 0) -> dict:
    """Period-4 motif LM data with VARIABLE real lengths (padding to seq_len):
    loss_mask marks real tokens, mirroring the reference's padded causal-LM
    batches."""
    import numpy as np

    rng = np.random.default_rng(seed)
    motif = rng.integers(2, vocab, size=(n, 4), dtype=np.int32)
    reps = int(np.ceil(seq_len / 4))
    ids = np.tile(motif, (1, reps))[:, :seq_len]
    lengths = rng.integers(seq_len // 2, seq_len + 1, size=n)
    mask = (np.arange(seq_len)[None, :] < lengths[:, None]).astype(np.int32)
    ids = ids * mask  # pad token = 0
    return {"input_ids": ids, "loss_mask": mask}


def training_function(args):
    import dataclasses

    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import Accelerator, DataLoader
    from accelerate_tpu.models import LlamaConfig, init_llama, llama_forward, llama_shard_rules

    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
        cpu=args.cpu,
        rng_seed=args.seed,
    )
    config = dataclasses.replace(LlamaConfig.tiny(), max_seq_len=args.seq_len)
    train = make_varlen_lm(args.train_size, args.seq_len, config.vocab_size, seed=0)
    params = init_llama(config, jax.random.PRNGKey(args.seed))
    train_dl = DataLoader(DictDataset(train), batch_size=args.batch_size,
                          shuffle=True, seed=args.seed)
    params, optimizer, train_dl = accelerator.prepare(
        params, optax.adam(args.lr), train_dl, shard_rules=llama_shard_rules()
    )

    # Token-weighted loss: sum-of-NLL over real tokens / EXPECTED tokens per
    # micro-batch (global batch tokens / accumulation steps). MultiSteps then
    # MEANS micro-grads, so the full window reproduces sum/total_tokens — the
    # reference reaches the same place by multiplying each micro-loss by
    # num_samples_in_epoch/num_items_in_batch (its script's loss re-weighting).
    expected_tokens_per_micro = None  # set from the first batch below

    def loss_fn(p, batch):
        ids, mask = batch["input_ids"], batch["loss_mask"]
        logits = llama_forward(p, ids, config)
        targets = jnp.roll(ids, shift=-1, axis=1)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        valid = (jnp.arange(ids.shape[1]) < ids.shape[1] - 1).astype(jnp.float32)[None, :]
        valid = valid * jnp.roll(mask, shift=-1, axis=1).astype(jnp.float32)
        return jnp.sum(nll * valid) / expected_tokens_per_micro

    step = accelerator.prepare_train_step(loss_fn, optimizer)
    opt_state = optimizer.opt_state
    last = None
    for epoch in range(args.epochs):
        for batch in train_dl:
            if expected_tokens_per_micro is None:
                # average real tokens per micro-batch over the dataset: a
                # STATIC normalizer (jit-friendly) that keeps token weighting
                # exact in expectation across the window
                import numpy as np

                total = float(np.asarray(train["loss_mask"]).sum())
                per_sample = total / len(train["loss_mask"])
                expected_tokens_per_micro = per_sample * batch["input_ids"].shape[0]
            with accelerator.accumulate():
                params, opt_state, metrics = step(params, opt_state, batch)
        last = float(metrics["loss"])
        accelerator.print(f"epoch {epoch}: loss {last:.4f}")
    return {"train_loss": last}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--seq-len", type=int, default=64)
    parser.add_argument("--gradient-accumulation-steps", type=int, default=2)
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
