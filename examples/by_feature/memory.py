"""Feature: OOM-safe batch-size search (reference ``examples/by_feature/memory.py``):
``find_executable_batch_size`` retries the decorated function with a halved
batch size whenever the device OOMs (XLA RESOURCE_EXHAUSTED), clearing caches
between attempts.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/by_feature/memory.py --cpu
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import add_common_args, build_tiny_bert_setup, evaluate_accuracy, maybe_force_cpu


def training_function(args):
    from accelerate_tpu import Accelerator, find_executable_batch_size

    accelerator = Accelerator(mixed_precision=args.mixed_precision, cpu=args.cpu,
                              rng_seed=args.seed)

    @find_executable_batch_size(starting_batch_size=args.starting_batch_size)
    def inner_training_loop(batch_size):
        accelerator.print(f"trying batch_size={batch_size}")
        accelerator.free_memory()
        args.batch_size = batch_size
        setup = build_tiny_bert_setup(args, accelerator)
        step = accelerator.prepare_train_step(setup["loss_fn"], setup["optimizer"])
        eval_step = accelerator.prepare_eval_step(setup["logits_fn"])
        params, opt_state = setup["params"], setup["optimizer"].opt_state
        for epoch in range(args.epochs):
            for batch in setup["train_dl"]:
                params, opt_state, _ = step(params, opt_state, batch)
        return evaluate_accuracy(accelerator, eval_step, params, setup["eval_dl"])

    acc = inner_training_loop()
    accelerator.print(f"accuracy {acc:.3f} at batch_size={args.batch_size}")
    return {"eval_accuracy": acc, "batch_size": args.batch_size}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--starting-batch-size", type=int, default=64)
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
