"""Feature: LocalSGD (reference ``examples/by_feature/local_sgd.py``): run K
purely-local optimizer steps per process, then average params — cuts collective
traffic Kx for communication-bound links (DCN cross-slice, not ICI).

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/by_feature/local_sgd.py --cpu
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import add_common_args, build_tiny_bert_setup, evaluate_accuracy, maybe_force_cpu


def training_function(args):
    from accelerate_tpu import Accelerator, LocalSGD

    accelerator = Accelerator(mixed_precision=args.mixed_precision, cpu=args.cpu,
                              rng_seed=args.seed)
    setup = build_tiny_bert_setup(args, accelerator)
    step = accelerator.prepare_train_step(setup["loss_fn"], setup["optimizer"])
    eval_step = accelerator.prepare_eval_step(setup["logits_fn"])
    params, opt_state = setup["params"], setup["optimizer"].opt_state
    with LocalSGD(accelerator, model=params,
                  local_sgd_steps=args.local_sgd_steps) as local_sgd:
        for epoch in range(args.epochs):
            for batch in setup["train_dl"]:
                params, opt_state, _ = step(params, opt_state, batch)
                params = local_sgd.step(params)  # averages every K steps
    acc = evaluate_accuracy(accelerator, eval_step, params, setup["eval_dl"])
    accelerator.print(f"accuracy {acc:.3f} (K={args.local_sgd_steps})")
    return {"eval_accuracy": acc}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--local-sgd-steps", type=int, default=8)
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
