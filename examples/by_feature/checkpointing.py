"""Feature: checkpoint/resume (reference ``examples/by_feature/checkpointing.py``):
save the full resumable state (params, optimizer, scheduler, sampler, RNG) each
epoch with rotation, then restore and continue.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/by_feature/checkpointing.py --cpu --output-dir /tmp/ckpt_demo
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import add_common_args, build_tiny_bert_setup, evaluate_accuracy, maybe_force_cpu


def training_function(args):
    import numpy as np

    from accelerate_tpu import Accelerator, ProjectConfiguration

    pc = ProjectConfiguration(project_dir=args.output_dir,
                              automatic_checkpoint_naming=True, total_limit=2)
    accelerator = Accelerator(mixed_precision=args.mixed_precision,
                              project_config=pc, cpu=args.cpu, rng_seed=args.seed)
    setup = build_tiny_bert_setup(args, accelerator)
    step = accelerator.prepare_train_step(setup["loss_fn"], setup["optimizer"])
    eval_step = accelerator.prepare_eval_step(setup["logits_fn"])
    params, opt_state = setup["params"], setup["optimizer"].opt_state

    for epoch in range(args.epochs):
        for batch in setup["train_dl"]:
            params, opt_state, _ = step(params, opt_state, batch)
        path = accelerator.save_state(params=params)
        accelerator.print(f"epoch {epoch}: checkpoint at {path}")
    acc_before = evaluate_accuracy(accelerator, eval_step, params, setup["eval_dl"])

    # resume: fresh params, restore the last checkpoint, verify parity
    restored = accelerator.load_state(path, params=params)
    opt_state = accelerator._optimizers[-1].opt_state
    acc_after = evaluate_accuracy(accelerator, eval_step, restored, setup["eval_dl"])
    assert abs(acc_before - acc_after) < 1e-6, (acc_before, acc_after)
    accelerator.print(f"resume parity OK: accuracy {acc_after:.3f}")
    # rotation kept at most total_limit checkpoints
    kept = [d for d in os.listdir(os.path.join(args.output_dir, "checkpoints"))
            if d.startswith("checkpoint_")]
    assert len(kept) <= 2, kept
    return {"eval_accuracy": acc_after}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--output-dir", default="/tmp/accelerate_tpu_ckpt_demo")
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
