"""Feature: profiling (reference ``examples/by_feature/profiler.py``):
``accelerator.profile()`` wraps ``jax.profiler`` — the trace dir holds
TensorBoard/Perfetto-compatible xplane dumps of the steps inside the context.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/by_feature/profiler.py --cpu --trace-dir /tmp/trace_demo
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import add_common_args, build_tiny_bert_setup, maybe_force_cpu


def training_function(args):
    from accelerate_tpu import Accelerator

    accelerator = Accelerator(mixed_precision=args.mixed_precision, cpu=args.cpu,
                              rng_seed=args.seed)
    setup = build_tiny_bert_setup(args, accelerator)
    step = accelerator.prepare_train_step(setup["loss_fn"], setup["optimizer"])
    params, opt_state = setup["params"], setup["optimizer"].opt_state

    def batches():
        # cycle epochs so short dataloaders still feed every profiled step
        while True:
            yield from setup["train_dl"]

    it = batches()
    # warm up OUTSIDE the profile window so the trace shows steady-state steps,
    # not the one-time XLA compile
    params, opt_state, metrics = step(params, opt_state, next(it))
    with accelerator.profile(trace_dir=args.trace_dir):
        for _ in range(3):
            params, opt_state, metrics = step(params, opt_state, next(it))
        float(metrics["loss"])  # force completion inside the window
    produced = any(os.scandir(args.trace_dir)) if os.path.isdir(args.trace_dir) else False
    accelerator.print(f"trace written to {args.trace_dir}: {produced}")

    # step-windowed schedule (reference ProfileKwargs wait/warmup/active/
    # repeat): only the active window of each cycle is traced — the way to
    # profile steady-state steps inside a long training loop
    from accelerate_tpu.utils import ProfileKwargs

    sched_cfg = ProfileKwargs(
        output_trace_dir=args.trace_dir + "_sched", wait=1, warmup=1, active=2, repeat=1
    )
    with accelerator.profile(sched_cfg) as prof:
        for _ in range(5):
            params, opt_state, metrics = step(params, opt_state, next(it))
            float(metrics["loss"])  # force completion before the step boundary
            prof.step()
    accelerator.print(f"scheduled traces: {prof.trace_dirs}")
    return {"trace_written": produced, "scheduled_traces": len(prof.trace_dirs)}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--trace-dir", default="/tmp/accelerate_tpu_trace_demo")
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
