"""Feature: gradient accumulation (reference
``examples/by_feature/gradient_accumulation.py``). Under jit the accumulate/
step boundary is a traced cond inside one compiled function — no python-side
no_sync bookkeeping; the effective update uses the mean gradient of
``gradient_accumulation_steps`` micro-batches.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/by_feature/gradient_accumulation.py --cpu
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import add_common_args, build_tiny_bert_setup, evaluate_accuracy, maybe_force_cpu


def training_function(args):
    from accelerate_tpu import Accelerator

    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
        cpu=args.cpu, rng_seed=args.seed,
    )
    setup = build_tiny_bert_setup(args, accelerator)
    step = accelerator.prepare_train_step(setup["loss_fn"], setup["optimizer"])
    eval_step = accelerator.prepare_eval_step(setup["logits_fn"])
    params, opt_state = setup["params"], setup["optimizer"].opt_state
    for epoch in range(args.epochs):
        for batch in setup["train_dl"]:
            # every call is a micro-batch; the optimizer really steps only on
            # accumulation boundaries (optax.MultiSteps inside)
            params, opt_state, metrics = step(params, opt_state, batch)
        acc = evaluate_accuracy(accelerator, eval_step, params, setup["eval_dl"])
        accelerator.print(f"epoch {epoch}: accuracy {acc:.3f}")
    return {"eval_accuracy": acc}


if __name__ == "__main__":
    parser = add_common_args(argparse.ArgumentParser(description=__doc__))
    parser.add_argument("--gradient-accumulation-steps", type=int, default=4)
    args = parser.parse_args()
    maybe_force_cpu(args)
    training_function(args)
