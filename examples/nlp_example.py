"""North-star example: BERT-style sequence-pair classification (MRPC-shaped).

TPU-native twin of the reference's ``examples/nlp_example.py`` (BERT-base MRPC):
same training shape — an Accelerator, a prepared dataloader/optimizer/scheduler,
a per-batch train loop with gradient accumulation, eval with
``gather_for_metrics`` — redesigned so the hot path is one jitted SPMD step.

With no network access this uses a synthetic paraphrase-detection task with the
exact MRPC tensor shapes (seq 128, labels {0,1}); pass ``--real-data`` to use a
locally cached GLUE/MRPC + tokenizer if present.

Run (CPU 8-dev):  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/nlp_example.py --cpu --model-size tiny
Run (TPU):        python examples/nlp_example.py
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_synthetic_mrpc(n: int, seq_len: int, vocab: int, seed: int = 0):
    """Learnable classification task with MRPC tensor shapes: a keyword token is
    planted at positions 1-4 and the label is a function of its identity. Chosen
    to be learnable by a tiny model in a few hundred steps so the example
    demonstrates real end-to-end learning without network access."""
    rng = np.random.default_rng(seed)
    half = seq_len // 2
    ids = rng.integers(10, vocab, size=(n, seq_len), dtype=np.int32)
    token_type = np.concatenate(
        [np.zeros((n, half), np.int32), np.ones((n, seq_len - half), np.int32)], axis=1
    )
    keywords = rng.integers(2, 10, size=n, dtype=np.int32)
    labels = (keywords >= 6).astype(np.int32)
    for pos in (1, 2, 3, 4):
        ids[:, pos] = keywords
    ids[:, 0] = 1  # [CLS]
    mask = np.ones((n, seq_len), np.int32)
    return {"input_ids": ids, "token_type_ids": token_type, "attention_mask": mask, "labels": labels}


class DictDataset:
    def __init__(self, data: dict):
        self.data = data

    def __len__(self):
        return len(self.data["labels"])

    def __getitem__(self, i):
        return {k: v[i] for k, v in self.data.items()}


def training_function(args):
    import optax

    from accelerate_tpu import Accelerator, DataLoader, ParallelismConfig
    from accelerate_tpu.models import BertConfig, bert_forward, bert_loss, bert_shard_rules, init_bert

    pc = None
    if args.dp or args.fsdp or args.tp > 1:
        pc = ParallelismConfig(
            dp_replicate_size=args.dp or 1,
            dp_shard_size=args.fsdp or 1,
            tp_size=args.tp,
        )
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
        parallelism_config=pc,
        log_with="jsonl" if args.project_dir else None,
        project_dir=args.project_dir,
        rng_seed=args.seed,
        cpu=args.cpu,
    )
    if args.project_dir:
        accelerator.init_trackers("nlp_example", config=vars(args))

    import dataclasses

    config = BertConfig.tiny() if args.model_size == "tiny" else BertConfig.base()
    config = dataclasses.replace(config, max_seq_len=args.seq_len, num_labels=2)
    train = make_synthetic_mrpc(args.train_size, args.seq_len, config.vocab_size, seed=0)
    test = make_synthetic_mrpc(args.eval_size, args.seq_len, config.vocab_size, seed=1)

    params = init_bert(config, jax.random.PRNGKey(args.seed))
    train_dl = DataLoader(DictDataset(train), batch_size=args.batch_size, shuffle=True, seed=args.seed)
    eval_dl = DataLoader(DictDataset(test), batch_size=args.batch_size)
    # schedule over *optimizer* steps: epochs x global steps / accumulation
    dp = max(len(jax.devices()) // args.tp, 1)
    steps_per_epoch = max(args.train_size // (args.batch_size * dp), 1)
    total_steps = max(args.epochs * steps_per_epoch // args.gradient_accumulation_steps, 2)
    optimizer = optax.adamw(
        optax.warmup_cosine_decay_schedule(0.0, args.lr, max(total_steps // 10, 1), total_steps)
    )

    params, optimizer, train_dl, eval_dl = accelerator.prepare(
        params, optimizer, train_dl, eval_dl, shard_rules=bert_shard_rules()
    )

    def loss_fn(p, batch):
        return bert_loss(p, batch, config)

    train_step = accelerator.prepare_train_step(loss_fn, optimizer)

    def eval_logits(p, batch):
        return bert_forward(p, batch, config)

    eval_step = accelerator.prepare_eval_step(eval_logits)

    opt_state = optimizer.opt_state
    samples = 0
    t_start = None
    for epoch in range(args.epochs):
        for step, batch in enumerate(train_dl):
            params, opt_state, metrics = train_step(params, opt_state, batch)
            if t_start is None:  # skip compile in throughput accounting; force a
                # host fetch (block_until_ready is unreliable on remote tunnels)
                float(np.asarray(metrics["loss"]))
                t_start = time.time()
            else:
                samples += batch["labels"].shape[0]
        # eval
        correct = total = 0
        for batch in eval_dl:
            logits = eval_step(params, batch)
            preds = jnp.argmax(logits, axis=-1)
            gathered = accelerator.gather_for_metrics({"preds": preds, "labels": batch["labels"]})
            correct += int(np.sum(np.asarray(gathered["preds"]) == np.asarray(gathered["labels"])))
            total += int(np.asarray(gathered["labels"]).shape[0])
        acc = correct / max(total, 1)
        accelerator.print(f"epoch {epoch}: eval accuracy {acc:.3f} (loss {float(metrics['loss']):.4f})")
        if args.project_dir:
            accelerator.log({"eval_accuracy": acc, "train_loss": float(metrics["loss"])}, step=epoch)
    float(np.asarray(metrics["loss"]))  # force completion before stopping the clock
    elapsed = time.time() - t_start if t_start else float("nan")
    throughput = samples / elapsed if elapsed and samples else 0.0
    n_chips = len(jax.devices())
    accelerator.print(
        f"throughput: {throughput:.1f} samples/s total, {throughput / n_chips:.1f} samples/s/chip"
    )
    accelerator.end_training()
    return {"eval_accuracy": acc, "samples_per_sec": throughput, "samples_per_sec_per_chip": throughput / n_chips}


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--mixed-precision", default="bf16", choices=["no", "fp16", "bf16"])
    parser.add_argument("--gradient-accumulation-steps", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=2e-4)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--model-size", default="tiny", choices=["tiny", "base"])
    parser.add_argument("--train-size", type=int, default=2048)
    parser.add_argument("--eval-size", type=int, default=512)
    parser.add_argument("--dp", type=int, default=0, help="dp_replicate size (0=auto)")
    parser.add_argument("--fsdp", type=int, default=0, help="dp_shard size")
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--project-dir", default=None)
    args = parser.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    training_function(args)


if __name__ == "__main__":
    main()
